"""SegformerTrainer — semantic-segmentation fine-tune engine (W6,
Scaling_model_training.ipynb).

Replaces the reference's per-worker HF ``Trainer`` over
``SegformerForSemanticSegmentation`` with explicit AdamW + identity LambdaLR
and 2-worker CPU-Gloo DDP (cc-47,51-53) with one jit-compiled SPMD step on a
``data`` mesh axis: the batch is sharded per device, gradient sync is the
psum XLA emits, and the decode head's BatchNorm statistics are cross-replica
by construction (XLA computes the batch moments over the global sharded
batch — stronger than torch DDP, which keeps per-replica BN stats).

Expected dataset columns (produced by the image-processor BatchMapper, the
``images_preprocessor`` analog, cc-38,42): ``pixel_values`` (HWC float) and
``labels`` (HW int, 255 = ignore).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .t5_trainer import TrainingArguments, _make_optimizer
from .trainer import BaseTrainer


def _collate_images(batch_df) -> Dict[str, np.ndarray]:
    from tpu_air.models.segformer.image_processor import collate_pixel_batch

    out = {"pixel_values": collate_pixel_batch(batch_df["pixel_values"])}
    if "labels" in batch_df.columns:
        out["labels"] = np.stack(
            [np.asarray(v, dtype=np.int32) for v in batch_df["labels"]]
        )
    return out


def segformer_train_loop(config: Dict[str, Any]) -> None:
    """SPMD training fn (runs inside the trial actor on its chip lease)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_air.models.segformer import (
        SegformerConfig,
        SegformerForSemanticSegmentation,
        segmentation_loss,
    )
    from tpu_air.parallel import make_mesh, visible_devices
    from tpu_air.train import session

    args: TrainingArguments = config.get("training_args") or TrainingArguments()
    for k in ("learning_rate", "num_train_epochs", "weight_decay"):
        if k in config:
            setattr(args, k, config[k])
    if "epochs" in config:
        args.num_train_epochs = config["epochs"]

    model_config: SegformerConfig = config["model_config"]
    preprocessor = config.get("_preprocessor")
    feature_extractor = config.get("feature_extractor")

    devs = visible_devices()
    dp = len(devs)
    mesh = make_mesh(("data",), (dp,), devices=devs)
    model = SegformerForSemanticSegmentation(model_config)
    ignore = model_config.semantic_loss_ignore_index

    train_ds = session.get_dataset_shard("train")
    eval_ds = session.get_dataset_shard("evaluation")
    if eval_ds is None:
        eval_ds = session.get_dataset_shard("eval")
    if train_ds is None:
        raise ValueError("SegformerTrainer requires a 'train' dataset")
    global_bs = args.per_device_train_batch_size * dp

    # -- variables ----------------------------------------------------------
    sample = _collate_images(
        next(train_ds.iter_batches(batch_size=1, batch_format="pandas"))
    )
    h, w = sample["pixel_values"].shape[1:3]

    resume_dir = config.get("resume_from_checkpoint")
    pretrained = config.get("pretrained_variables")
    if resume_dir:
        ckpt = Checkpoint.from_directory(resume_dir)
        params = ckpt.get_params()
        extras = ckpt._load_extras() or {}
        bstats = extras.get("batch_stats") or {}
    elif pretrained is not None:
        params, bstats = pretrained["params"], pretrained.get("batch_stats", {})
    else:
        init = model.init(
            jax.random.PRNGKey(args.seed),
            jnp.zeros((1, h, w, model_config.num_channels)),
        )
        params, bstats = init["params"], init.get("batch_stats", {})

    n_train = train_ds.count()
    steps_per_epoch = max(1, n_train // global_bs)
    if args.max_steps_per_epoch:
        steps_per_epoch = min(steps_per_epoch, args.max_steps_per_epoch)
    tx = _make_optimizer(args, steps_per_epoch * args.num_train_epochs)

    rep = NamedSharding(mesh, P())
    batch_sharding = NamedSharding(mesh, P("data"))
    params = jax.device_put(params, rep)
    bstats = jax.device_put(bstats, rep)
    opt_state = tx.init(params)

    # -- steps --------------------------------------------------------------
    from functools import partial

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(p, bs, o, px, lb, rng):
        rng, sub = jax.random.split(rng)

        def lf(pp):
            logits, upd = model.apply(
                {"params": pp, "batch_stats": bs},
                px,
                deterministic=False,
                rngs={"dropout": sub},
                mutable=["batch_stats"],
            )
            return segmentation_loss(logits, lb, ignore), upd["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(lf, has_aux=True)(p)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, new_bs, o, loss, rng

    @jax.jit
    def eval_step(p, bs, px, lb):
        logits = model.apply({"params": p, "batch_stats": bs}, px)
        return segmentation_loss(logits, lb, ignore)

    def put(b):
        return {
            k: jax.device_put(jnp.asarray(v), batch_sharding) for k, v in b.items()
        }

    rng = jax.device_put(jax.random.PRNGKey(args.seed + 1), rep)

    # -- epochs -------------------------------------------------------------
    for epoch in range(int(args.num_train_epochs)):
        t0 = time.time()
        losses, nsteps, nimg = [], 0, 0
        for batch_df in train_ds.iter_batches(
            batch_size=global_bs, batch_format="pandas", drop_last=True
        ):
            b = put(_collate_images(batch_df))
            params, bstats, opt_state, loss, rng = train_step(
                params, bstats, opt_state, b["pixel_values"], b["labels"], rng
            )
            losses.append(loss)
            nsteps += 1
            nimg += global_bs
            if args.max_steps_per_epoch and nsteps >= args.max_steps_per_epoch:
                break
        dt = time.time() - t0
        train_loss = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
        metrics: Dict[str, Any] = {
            "epoch": epoch + 1,
            "loss": train_loss,
            "steps": nsteps,
            "train_images_per_sec": nimg / dt if dt > 0 else 0.0,
        }

        if eval_ds is not None and args.evaluation_strategy == "epoch":
            tot, cnt = 0.0, 0
            for batch_df in eval_ds.iter_batches(
                batch_size=global_bs, batch_format="pandas", drop_last=True
            ):
                b = put(_collate_images(batch_df))
                tot += float(eval_step(params, bstats, b["pixel_values"], b["labels"]))
                cnt += 1
            if cnt:
                metrics["eval_loss"] = tot / cnt

        ckpt = None
        if args.save_strategy == "epoch":
            ckpt = Checkpoint.from_model(
                model_config=model_config,
                params=params,
                preprocessor=preprocessor,
                metrics=metrics,
                extras={
                    "batch_stats": jax.tree_util.tree_map(np.asarray, bstats),
                    **({"feature_extractor": feature_extractor} if feature_extractor else {}),
                },
            )
        session.report(metrics, checkpoint=ckpt)


class SegformerTrainer(BaseTrainer):
    """Drop-in for the reference's HuggingFaceTrainer-on-SegFormer config
    (Scaling_model_training.ipynb:cc-51-52)."""

    _name_prefix = "SegformerTrainer"

    def __init__(
        self,
        *,
        model_config=None,
        training_args: Optional[TrainingArguments] = None,
        pretrained_variables=None,
        feature_extractor=None,
        trainer_init_config: Optional[Dict[str, Any]] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if model_config is None:
            from tpu_air.models.segformer import SegformerConfig

            model_config = SegformerConfig.mit_b0()
        self.model_config = model_config
        self.training_args = training_args or TrainingArguments(
            learning_rate=1e-4, weight_decay=0.0
        )
        self.pretrained_variables = pretrained_variables
        self.feature_extractor = feature_extractor
        self.trainer_init_config = trainer_init_config or {}

    def _training_fn(self):
        return segformer_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        cfg = dict(self.trainer_init_config)
        cfg["model_config"] = self.model_config
        cfg["training_args"] = self.training_args
        if self.pretrained_variables is not None:
            cfg["pretrained_variables"] = self.pretrained_variables
        if self.feature_extractor is not None:
            cfg["feature_extractor"] = self.feature_extractor
        return cfg
