"""Typed training configs (tier-1 config surface, SURVEY.md §5 config notes).

Parity targets: ``ScalingConfig(num_workers, use_gpu)``
(Model_finetuning…ipynb:cc-40) — TPU-native fields added per SURVEY.md §5
("ScalingConfig gains topology/sub-mesh fields"); ``RunConfig`` /
``CheckpointConfig(num_to_keep, checkpoint_score_attribute,
checkpoint_score_order)`` (cc-40); ``FailureConfig`` (§5 failure-detection
notes — absent in the reference workloads but part of the Train surface).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How much of the slice a run uses.

    ``num_workers`` is the data-parallel degree (per-worker dataset shards,
    Model_finetuning…ipynb:cc-29).  On TPU a "worker" is a chip in the run's
    sub-mesh, not a GPU process: the trainer jits ONE SPMD step over a mesh of
    ``num_workers × num_chips_per_worker`` chips (SURVEY.md §7 stance).
    """

    num_workers: int = 1
    use_tpu: bool = True
    num_chips_per_worker: int = 1
    # Tensor-parallel degree: each data-parallel worker's model is sharded
    # over this many chips (the ``model`` mesh axis; rules in
    # tpu_air/parallel/sharding.py).  The reference has no TP (SURVEY.md §2C)
    # but the north-star FLAN-T5-XL cannot run replicated — TP is a config
    # change here, per SURVEY.md §7's mesh stance.
    model_parallel: Optional[int] = None
    # Sequence-parallel degree (long-context): each data-parallel worker's
    # CONTEXT is sharded over this many chips (the ``sequence`` mesh axis;
    # ring attention over ICI — ops/ring_attention.py).  Absent from the
    # reference (SURVEY.md §2C SP row: explicit non-goal there) but
    # first-class here; consumed by LMTrainer.
    sequence_parallel: Optional[int] = None
    topology: Optional[str] = None  # e.g. "v4-32"; informational for placement
    resources_per_worker: Optional[Dict[str, float]] = None
    # GPU-era alias accepted for drop-in compatibility (cc-40's use_gpu=True)
    use_gpu: Optional[bool] = None

    def __post_init__(self):
        if self.use_gpu is not None:
            self.use_tpu = bool(self.use_gpu)
        # validate BEFORE the `or 1` defaulting: an explicit 0 must raise,
        # not silently train replicated
        if self.model_parallel is not None and self.model_parallel < 1:
            raise ValueError("model_parallel must be >= 1")
        if self.sequence_parallel is not None and self.sequence_parallel < 1:
            raise ValueError("sequence_parallel must be >= 1")
        self.model_parallel = self.model_parallel or 1
        self.sequence_parallel = self.sequence_parallel or 1
        # a worker's chips must cover the PRODUCT of its in-worker axes —
        # validating against each degree separately would silently accept
        # model_parallel=2, sequence_parallel=2 on 2 chips
        axes = self.model_parallel * self.sequence_parallel
        if self.num_chips_per_worker == 1:
            self.num_chips_per_worker = axes
        elif self.num_chips_per_worker % axes != 0:
            raise ValueError(
                f"num_chips_per_worker={self.num_chips_per_worker} is not a "
                f"multiple of model_parallel x sequence_parallel = {axes}"
            )

    @property
    def total_chips(self) -> int:
        return self.num_workers * self.num_chips_per_worker if self.use_tpu else 0


@dataclass
class CheckpointConfig:
    """Score-based checkpoint retention (cc-40: keep best-1 by min
    eval_loss)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "min"
    # Live-serving handoff: when set, every retained checkpoint's param tree
    # is ALSO published to the versioned WeightStore at this root (manifest
    # last, per-tensor checksums — tpu_air/serve/weights.py), where a
    # WeightsController canary-gates it onto serving replicas.  The store is
    # GC'd to ``num_to_keep`` full versions (default 2 when unset) so the
    # serving fleet always has the previous version to roll back to.
    publish_weights_to: Optional[str] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("min", "max"):
            raise ValueError("checkpoint_score_order must be 'min' or 'max'")


@dataclass
class FailureConfig:
    """Retry policy: restart a failed run from its latest checkpoint
    (SURVEY.md §5: 'trainer restart from latest checkpoint')."""

    max_failures: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # defaults to ~/tpu_air_results
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    verbose: int = 1
