"""Trainers: single-controller SPMD training runs (L3).

The reference's Ray Train runs a trainable actor which spawns a WorkerGroup of
N one-GPU processes coordinated by NCCL DDP (SURVEY.md §3.1).  TPU-native
design (§7 architecture stance): the worker group collapses into **one
process holding a chip lease** that jits a single SPMD step over a
``data``-axis mesh — gradient sync is a compiler-emitted psum over ICI, not a
runtime service.  What remains of the reference shape:

* the run executes in a dedicated **trial actor** (failure isolation, the
  driver stays responsive, Tune can run many concurrently on disjoint
  sub-meshes);
* per-worker dataset shards (cc-29) become per-device shards of the batch
  axis, handled inside the jitted step;
* ``trainer.fit() -> Result`` with metrics/checkpoint/error
  (Introduction…ipynb:cc-36), retries from the latest checkpoint up to
  ``FailureConfig.max_failures`` (§5 failure notes), and
  ``resume_from_checkpoint`` (Introduction…ipynb:cc-33).
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable, Dict, Optional

import tpu_air

from .checkpoint import Checkpoint
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .result import Result
from .session import Session, StopTrial, _set_active


def _default_storage() -> str:
    return os.environ.get(
        "TPU_AIR_RESULTS_DIR", os.path.join(os.path.expanduser("~"), "tpu_air_results")
    )


def _touch(path: str) -> None:
    try:
        with open(path, "w") as f:
            f.write("revoked")
    except OSError:
        pass


def _shrunk_scaling(sc: ScalingConfig, chips_per_host: int):
    """The next-smaller legal lease shape after a preemption: halve the
    DATA-parallel degree (the model/sequence axes must survive intact — a
    TP-sharded model cannot lose chips) until the total is a legal lease
    shape (whole-host multiples above one host).  ``None`` when already at
    one worker — the run cannot shrink further and must wait for the same
    shape to free up."""
    import dataclasses

    workers = sc.num_workers
    while workers > 1:
        workers //= 2
        total = workers * sc.num_chips_per_worker
        if total <= chips_per_host or total % chips_per_host == 0:
            return dataclasses.replace(sc, num_workers=workers)
    return None


def _scan_latest_checkpoint(run_dir: str):
    """Newest ``checkpoint_*`` directory under ``run_dir`` as the
    ``(path, metrics)`` pair the session would have reported.  The recovery
    source when the trial ACTOR crashed: its in-memory checkpoint list died
    with it, but the retained directories are durable (the iteration-numbered
    names sort chronologically)."""
    try:
        dirs = sorted(
            d for d in os.listdir(run_dir)
            if d.startswith("checkpoint_")
            and os.path.isdir(os.path.join(run_dir, d)))
    except OSError:
        return None
    if not dirs:
        return None
    return (os.path.join(run_dir, dirs[-1]), {})


@tpu_air.remote
class _TrialRunner:
    """Actor hosting one training run on its chip lease."""

    def __init__(self):
        pass

    def run(
        self,
        training_fn: Callable[[Dict[str, Any]], None],
        config: Dict[str, Any],
        run_dir: str,
        datasets: Dict[str, Any],
        checkpoint_config: CheckpointConfig,
        world_size: int,
        trial_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        decision_cb = None
        if trial_id is not None:
            from tpu_air.core import runtime as _rt

            store = _rt.current_worker().store if _rt.current_worker() else None

            def decision_cb(rec, seq, _store=store, _tid=trial_id):
                # Stream the report to the driver (Tune watches these), then
                # block briefly for the scheduler's decision ack so a prune
                # lands BEFORE the next epoch/round spends compute (a fast
                # trial must not outrun an async stop marker).  If the driver
                # is slow the trial proceeds and the async `{tid}-stop`
                # marker still catches it at a later report.
                _store.put(rec, f"{_tid}-report-{seq}")
                try:
                    ok = bool(_store.get(f"{_tid}-ack-{seq}", timeout=5.0))
                    _store.delete(f"{_tid}-ack-{seq}")
                    if not ok:
                        return False
                except TimeoutError:
                    pass
                return not _store.contains(f"{_tid}-stop")

        session = Session(
            run_dir=run_dir,
            checkpoint_config=checkpoint_config,
            datasets=datasets,
            config=config,
            world_size=world_size,
            decision_cb=decision_cb,
        )
        _set_active(session)
        out: Dict[str, Any] = {"error": None, "stopped": False}
        try:
            training_fn(config)
        except StopTrial:
            out["stopped"] = True
        except BaseException as e:  # noqa: BLE001 - trial boundary
            out["error"] = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
        finally:
            _set_active(None)
            for sink in session.sinks:
                if hasattr(sink, "close"):
                    sink.close()
        out["history"] = session.history
        out["checkpoints"] = [(p, m) for p, m in session.checkpoints]
        best = session.best_checkpoint()
        out["best_checkpoint"] = best
        out["latest_checkpoint"] = session.latest_checkpoint()
        return out


class _BroadcastDataset:
    """Pandas-backed dataset shim for SPMD-multihost fit broadcasts.

    Host agents have no connection to the driver's object store, so
    datasets are materialized on the driver and shipped by value inside the
    broadcast thunk.  Covers the surface the built-in train loops use
    (iter_batches / count / to_pandas); every host iterates the SAME rows
    in the same order, and the loop's sharded batch placement gives each
    host's devices their slice."""

    def __init__(self, df):
        self._df = df.reset_index(drop=True)

    def count(self) -> int:
        return len(self._df)

    def to_pandas(self):
        return self._df

    def iter_batches(self, batch_size: int, batch_format: str = "pandas",
                     drop_last: bool = False):
        n = len(self._df)
        end = n - (n % batch_size) if drop_last else n
        for i in range(0, max(end, 0), batch_size):
            yield self._df.iloc[i : i + batch_size]


class BaseTrainer:
    """Shared fit() machinery.  Subclasses provide ``_training_fn()`` (a
    picklable function of one ``config`` dict that uses the session API)."""

    _name_prefix = "Trainer"

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        preprocessor=None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.preprocessor = preprocessor
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}

    # -- subclass surface ---------------------------------------------------
    def _training_fn(self) -> Callable[[Dict[str, Any]], None]:
        raise NotImplementedError

    def _train_loop_config(self) -> Dict[str, Any]:
        return {}

    # -- preprocessing (fit-on-train, §1-L2 persistent preprocessor) --------
    def _preprocess(self) -> Dict[str, Any]:
        datasets = dict(self.datasets)
        if self.preprocessor is not None:
            train = datasets.get("train")
            if train is not None and self.preprocessor._is_fittable:
                if not self.preprocessor.check_is_fitted():
                    self.preprocessor.fit(train)
            for k, ds in list(datasets.items()):
                datasets[k] = self.preprocessor.transform(ds)
        return datasets

    def fit(self) -> Result:
        tpu_air.init()
        name = self.run_config.name or (
            f"{self._name_prefix}_{int(time.time())}_{os.urandom(3).hex()}"
        )
        run_dir = os.path.join(
            self.run_config.storage_path or _default_storage(), name
        )
        os.makedirs(run_dir, exist_ok=True)
        datasets = self._preprocess()
        return self._run_attempts(datasets, run_dir, trial_id=None)

    # -- attempt loop (failure recovery) ------------------------------------
    def _run_attempts(
        self,
        datasets: Dict[str, Any],
        run_dir: str,
        trial_id: Optional[str],
        extra_config: Optional[Dict[str, Any]] = None,
    ) -> Result:
        sc = self.scaling_config
        rc = self.run_config
        resume = self.resume_from_checkpoint
        config = dict(self._train_loop_config())
        if extra_config:
            config.update(extra_config)
        config["_preprocessor"] = self.preprocessor
        config["_scaling_config"] = sc  # mesh topology source for the loop

        # SPMD-multihost path (docs/MULTIHOST.md §3): a lease larger than one
        # host cannot run in a single local actor — the jitted step must be
        # ENTERED by every owning host.  Route the whole training function
        # through the cluster's agent plane instead of a trial actor.
        from tpu_air.parallel import distributed as _dist

        cluster = _dist.active_cluster()
        rt = tpu_air.core.runtime.get_runtime()
        if (
            cluster is not None
            and getattr(cluster, "num_processes", 1) > 1
            and (sc.total_chips or 0) > rt.chips_per_host
        ):
            return self._run_spmd_multihost(
                datasets, run_dir, config, cluster, rt, resume,
                trial_id=trial_id,
            )
        return self._run_actor_attempts(
            datasets, run_dir, trial_id, config, resume, sc, rc
        )

    def _run_actor_attempts(self, datasets, run_dir, trial_id, config,
                            resume, sc, rc) -> Result:
        """The single-actor attempt loop (also the landing path when an
        elastic preemption shrinks a multihost lease down to one host)."""
        max_failures = rc.failure_config.max_failures
        attempt = 0
        while True:
            if resume is not None:
                config["resume_from_checkpoint"] = (
                    resume.to_directory() if isinstance(resume, Checkpoint) else resume
                )
            runner = _TrialRunner.options(
                num_chips=sc.total_chips or None, num_cpus=0
            ).remote()
            try:
                out = tpu_air.get(
                    runner.run.remote(
                        self._training_fn(),
                        config,
                        run_dir,
                        datasets,
                        rc.checkpoint_config,
                        sc.num_workers,
                        trial_id,
                    )
                )
                err = out.get("error")
            except tpu_air.RemoteError as e:  # actor crashed outright
                # the crash took the session's in-memory checkpoint list with
                # it — recover the newest on-disk checkpoint so the retry
                # RESUMES instead of silently restarting from scratch
                out = {"history": [], "checkpoints": [], "best_checkpoint": None,
                       "latest_checkpoint": _scan_latest_checkpoint(run_dir)}
                err = str(e)
            finally:
                tpu_air.kill(runner)

            if err is None:
                return self._assemble(out, run_dir, config, None)
            latest = out.get("latest_checkpoint")
            if attempt < max_failures:
                attempt += 1
                if latest:
                    resume = Checkpoint.from_directory(latest[0])
                continue
            return self._assemble(
                out, run_dir, config, RuntimeError(err)
            )

    def _run_spmd_multihost(
        self, datasets, run_dir, config, cluster, rt, resume, trial_id=None
    ) -> Result:
        """Run the training fn on EVERY host of the active cluster in
        lockstep over a cross-host chip lease.  Host 0 (this process) keeps
        the real session (reporting, checkpoint retention); other hosts run
        throwaway replicas whose only output is their error status.

        FailureConfig semantics match the actor path for TRAINING errors
        (exceptions inside the training fn): retry from the latest
        checkpoint up to ``max_failures``.  Infrastructure failures (a dead
        host agent) propagate — the same dead cluster would fail every
        retry.

        ELASTIC preemption (docs/RESILIENCE.md): a revoked chip lease —
        cold (``LeaseRevokedError`` at acquisition) or graceful (a notice
        mid-trial, observed by every host's session at its next report) —
        is not a training failure.  The run checkpoint-retains as usual,
        re-leases at a possibly SMALLER data-parallel width (capacity just
        left the pool), and resumes from the newest retained checkpoint.
        Preemption retries are budgeted separately from ``max_failures``
        so a preempted trial does not burn its crash-recovery budget."""
        from tpu_air.faults.plan import LeaseRevokedError

        sc = self.scaling_config
        rc = self.run_config
        max_failures = rc.failure_config.max_failures
        max_preemptions = 3
        attempt = 0
        preemptions = 0
        marker = os.path.join(run_dir, "_lease_revoked")

        def shrink_and_resume(latest):
            nonlocal sc, resume
            smaller = _shrunk_scaling(sc, rt.chips_per_host)
            if smaller is not None:
                sc = smaller
                config["_scaling_config"] = sc
            if latest:
                resume = Checkpoint.from_directory(latest[0])

        while True:
            if sc.total_chips <= rt.chips_per_host:
                # the elastic shrink landed on a single host: the agent
                # plane is the wrong vehicle now (the lease no longer
                # spans hosts) — finish the run on the actor path
                return self._run_actor_attempts(
                    datasets, run_dir, trial_id, config, resume, sc,
                    self.run_config
                )
            if resume is not None:
                config["resume_from_checkpoint"] = (
                    resume.to_directory()
                    if isinstance(resume, Checkpoint) else resume
                )
            try:
                os.remove(marker)
            except OSError:
                pass
            try:
                lease = rt.lease_chips(sc.total_chips, timeout=300.0)
            except LeaseRevokedError:
                # cold revocation at acquisition: nothing ran, nothing is
                # lost — re-lease smaller and resume
                if preemptions >= max_preemptions:
                    raise
                preemptions += 1
                shrink_and_resume(_scan_latest_checkpoint(run_dir))
                continue
            # graceful preemption: the notice writes the marker (run_dir
            # is on shared storage), every host's session sees it at its
            # next report and raises LeaseRevokedError out of the loop at
            # the SAME iteration — an SPMD-consistent stop point
            lease.on_revoke(lambda notice_s, _m=marker: _touch(_m))
            try:
                out, error = self._run_spmd_leased(
                    datasets, run_dir, config, cluster, rc, sc, lease
                )
            finally:
                rt.release_chips(lease)
            if error is None:
                return self._assemble(out, run_dir, config, None)
            latest = out.get("latest_checkpoint")
            if (lease.revoking and "LeaseRevokedError" in str(error)
                    and preemptions < max_preemptions):
                preemptions += 1
                shrink_and_resume(latest)
                continue
            if attempt < max_failures:
                attempt += 1
                if latest:
                    resume = Checkpoint.from_directory(latest[0])
                continue
            return self._assemble(out, run_dir, config, error)

    def _run_spmd_leased(self, datasets, run_dir, config, cluster, rc, sc,
                         lease):
        """One multihost attempt; returns (host-0 out dict, error|None)."""
        training_fn = self._training_fn()
        dfs = {
            k: ds.to_pandas() for k, ds in datasets.items() if ds is not None
        }
        ckpt_cfg = rc.checkpoint_config
        world = sc.num_workers

        def spmd_fit(
            training_fn=training_fn, config=config, dfs=dfs, lease=lease,
            run_dir=run_dir, ckpt_cfg=ckpt_cfg, world=world,
        ):
            import tempfile
            import traceback as _tb

            import jax

            from tpu_air.train.session import Session, _set_active
            from tpu_air.train.trainer import _BroadcastDataset

            pid = jax.process_index()
            prev_lease = os.environ.get("TPU_AIR_CHIP_IDS")
            os.environ["TPU_AIR_CHIP_IDS"] = ",".join(str(c) for c in lease)

            # graceful-preemption stop point: the driver's on_revoke hook
            # touches this marker; every host checks it at report() — the
            # same iteration on every host, so the SPMD program counters
            # never diverge — and unwinds with LeaseRevokedError, which
            # _run_spmd_multihost treats as "shrink + resume", not failure
            marker = os.path.join(run_dir, "_lease_revoked")

            def _preempt_check(rec, seq, _m=marker):
                if os.path.exists(_m):
                    from tpu_air.faults.plan import LeaseRevokedError

                    raise LeaseRevokedError(
                        "chip lease revoked mid-trial (preemption notice)"
                    )
                return True

            try:
                ds = {k: _BroadcastDataset(df) for k, df in dfs.items()}
                rd = run_dir if pid == 0 else tempfile.mkdtemp(
                    prefix="tpu_air-spmd-replica-"
                )
                session = Session(
                    run_dir=rd, checkpoint_config=ckpt_cfg, datasets=ds,
                    config=config, world_size=world,
                    sinks=None if pid == 0 else [],
                    decision_cb=_preempt_check,
                )
                _set_active(session)
                out = {"error": None, "stopped": False}
                try:
                    training_fn(config)
                except BaseException as e:  # noqa: BLE001 - trial boundary
                    out["error"] = (
                        f"{type(e).__name__}: {e}\n{_tb.format_exc()}"
                    )
                finally:
                    _set_active(None)
                    for sink in session.sinks:
                        if hasattr(sink, "close"):
                            sink.close()
                if pid != 0:
                    # replica output is discarded — reclaim the throwaway
                    # run dir (it holds full checkpoint copies)
                    import shutil

                    shutil.rmtree(rd, ignore_errors=True)
                    return {"error": out["error"], "replica": pid}
                out["history"] = session.history
                out["checkpoints"] = [(p, m) for p, m in session.checkpoints]
                out["best_checkpoint"] = session.best_checkpoint()
                out["latest_checkpoint"] = session.latest_checkpoint()
                return out
            finally:
                if prev_lease is None:
                    os.environ.pop("TPU_AIR_CHIP_IDS", None)
                else:
                    os.environ["TPU_AIR_CHIP_IDS"] = prev_lease

        outs = cluster.run(spmd_fit)
        out = outs[0]
        errors = [o["error"] for o in outs if o.get("error")]
        error = RuntimeError("\n---\n".join(errors)) if errors else None
        return out, error

    def _assemble(self, out, run_dir, config, error) -> Result:
        best = out.get("best_checkpoint")
        history = out.get("history", [])
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=Checkpoint.from_directory(best[0]) if best else None,
            error=error,
            path=run_dir,
            metrics_history=history,
            best_checkpoints=[
                (Checkpoint.from_directory(p), m) for p, m in out.get("checkpoints", [])
            ],
            config={k: v for k, v in config.items() if not k.startswith("_")},
        )


class JaxTrainer(BaseTrainer):
    """Generic function trainer: runs ``train_loop_per_worker(config)`` once
    as the SPMD controller of the run's sub-mesh.  The loop uses
    ``tpu_air.train.session`` (report / get_dataset_shard / get_config) —
    the TorchTrainer(train_loop_per_worker) analog with the WorkerGroup
    folded into the mesh."""

    _name_prefix = "JaxTrainer"

    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict[str, Any]], None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}

    def _training_fn(self):
        return self.train_loop_per_worker

    def _train_loop_config(self):
        return dict(self.train_loop_config)
