"""T5Trainer — the flagship fine-tune engine (W1/W5, Model_finetuning…ipynb).

Replaces the reference's per-worker HF ``Trainer`` factory + NCCL DDP
(trainer_init_per_worker, cc-34; "PyTorch DDP synchronizes their weights",
cc-29) with one jit-compiled SPMD train step over a ``(data, model)`` mesh:

* batch sharded on ``data`` — per-device shards replace per-worker dataset
  shards; the gradient all-reduce is the psum XLA emits for replicated
  params (ICI, not NCCL);
* optional tensor parallelism via the ``model`` axis (param rules in
  tpu_air/parallel/sharding.py) — a config change, per SURVEY.md §2C;
* params donated through the step (no copies), activations in
  ``model_config.dtype`` (bf16 on TPU — the fp16-on-GPU analog);
* per-epoch eval / checkpoint / report matching the HF epoch strategies the
  reference configures (evaluation_strategy/save_strategy/logging_strategy
  ="epoch", cc-34), metric names ``loss``/``eval_loss`` (cc-40).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .trainer import BaseTrainer


@dataclass
class TrainingArguments:
    """The subset of HF TrainingArguments the reference exercises (cc-34),
    plus TPU-native knobs."""

    learning_rate: float = 2e-5
    per_device_train_batch_size: int = 2
    per_device_eval_batch_size: Optional[int] = None
    num_train_epochs: int = 4
    weight_decay: float = 0.01
    warmup_steps: int = 0
    max_grad_norm: float = 1.0
    optimizer: str = "adamw"  # or "adafactor"
    lr_scheduler_type: str = "constant"  # or "linear" / "cosine" decay to 0
    seed: int = 42
    evaluation_strategy: str = "epoch"
    save_strategy: str = "epoch"
    logging_strategy: str = "epoch"
    max_steps_per_epoch: Optional[int] = None  # test dial
    tensor_parallelism: int = 1
    remat: bool = False  # jax.checkpoint the decoder layers (HBM for FLOPs)
    disable_tqdm: bool = True  # accepted for parity; no tqdm either way

    def __post_init__(self):
        if self.per_device_eval_batch_size is None:
            self.per_device_eval_batch_size = self.per_device_train_batch_size


def collate(batch_df, keys, seq_len: Optional[int] = None) -> Dict[str, np.ndarray]:
    """DataFrame of per-row token lists → stacked int32 arrays."""
    out = {}
    for k in keys:
        col = [np.asarray(v, dtype=np.int32) for v in batch_df[k]]
        out[k] = np.stack(col)
        if seq_len is not None and out[k].shape[1] != seq_len:
            raise ValueError(
                f"column {k} has seq len {out[k].shape[1]}, expected {seq_len}"
            )
    return out


def _make_optimizer(args: TrainingArguments, total_steps: int):
    import optax

    decay_steps = max(1, total_steps - args.warmup_steps)
    if args.lr_scheduler_type == "linear":
        decay = optax.linear_schedule(args.learning_rate, 0.0, decay_steps)
    elif args.lr_scheduler_type == "cosine":
        decay = optax.cosine_decay_schedule(args.learning_rate, decay_steps)
    else:
        decay = optax.constant_schedule(args.learning_rate)
    if args.warmup_steps > 0:
        lr = optax.join_schedules(
            [optax.linear_schedule(0.0, args.learning_rate, args.warmup_steps), decay],
            [args.warmup_steps],
        )
    else:
        lr = decay
    if args.optimizer == "adafactor":
        tx = optax.adafactor(learning_rate=lr)
    else:
        tx = optax.adamw(
            learning_rate=lr, weight_decay=args.weight_decay, b1=0.9, b2=0.999
        )
    if args.max_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(args.max_grad_norm), tx)
    return tx


def t5_train_loop(config: Dict[str, Any]) -> None:
    """The SPMD training function (runs inside the trial actor, on its chip
    lease). Uses the session API for data/report."""
    import jax
    import jax.numpy as jnp

    from tpu_air.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
        cross_entropy_loss,
        shift_right,
    )
    from tpu_air.parallel import make_mesh, visible_devices
    from tpu_air.parallel.sharding import shard_params
    from tpu_air.train import session
    from jax.sharding import NamedSharding, PartitionSpec as P

    args: TrainingArguments = config.get("training_args") or TrainingArguments(
        **{
            k: v
            for k, v in config.items()
            if k in TrainingArguments.__dataclass_fields__
        }
    )
    # Tune-style overrides arrive as plain dict keys (cc-34 lines 75-79:
    # config.get("learning_rate", ...) pattern)
    for k in ("learning_rate", "num_train_epochs", "weight_decay"):
        if k in config:
            setattr(args, k, config[k])
    if "epochs" in config:
        args.num_train_epochs = config["epochs"]

    model_config: T5Config = config["model_config"]
    tokenizer = config.get("tokenizer")
    preprocessor = config.get("_preprocessor")

    # -- mesh ---------------------------------------------------------------
    # TP degree: ScalingConfig.model_parallel is the user-facing knob
    # (SURVEY.md §7 "TP is a config change"); TrainingArguments.tensor_
    # parallelism remains as the loop-level override for raw JaxTrainer use.
    devs = visible_devices()
    sc = config.get("_scaling_config")
    sc_mp = getattr(sc, "model_parallel", None) or 1
    # ScalingConfig wins when it requests real TP; otherwise the loop-level
    # TrainingArguments.tensor_parallelism override (raw JaxTrainer-style
    # usage) still applies — ScalingConfig's default of 1 must not mask it.
    tp = sc_mp if sc_mp > 1 else max(1, args.tensor_parallelism)
    if tp > len(devs):
        raise ValueError(
            f"model_parallel={tp} exceeds the {len(devs)} visible devices of "
            f"this run's chip lease"
        )
    dp = max(1, len(devs) // tp)
    mesh = make_mesh(("data", "model"), (dp, tp), devices=devs[: dp * tp])
    ndev = dp * tp

    model = T5ForConditionalGeneration(model_config)
    pad_id = model_config.pad_token_id
    start_id = model_config.decoder_start_token_id

    # -- data ---------------------------------------------------------------
    train_ds = session.get_dataset_shard("train")
    eval_ds = session.get_dataset_shard("evaluation")
    if eval_ds is None:
        eval_ds = session.get_dataset_shard("eval")
    if train_ds is None:
        raise ValueError("T5Trainer requires a 'train' dataset")
    global_bs = args.per_device_train_batch_size * dp
    keys = ["input_ids", "attention_mask", "labels"]

    # -- params -------------------------------------------------------------
    sample = next(train_ds.iter_batches(batch_size=2, batch_format="pandas"))
    sample_batch = collate(sample, keys)
    seq_len = sample_batch["input_ids"].shape[1]

    resume_dir = config.get("resume_from_checkpoint")
    pretrained = config.get("pretrained_params")
    if resume_dir:
        params = Checkpoint.from_directory(resume_dir).get_params()
    elif pretrained is not None:
        params = pretrained
    else:
        init_rng = jax.random.PRNGKey(args.seed)
        dummy = jnp.ones((1, 8), jnp.int32)
        params = model.init(init_rng, dummy, dummy, dummy[:, :4])["params"]

    n_train = train_ds.count()
    steps_per_epoch = max(1, n_train // global_bs)
    if args.max_steps_per_epoch:
        steps_per_epoch = min(steps_per_epoch, args.max_steps_per_epoch)
    tx = _make_optimizer(args, steps_per_epoch * args.num_train_epochs)

    params = shard_params(params, mesh)
    opt_state = tx.init(params)
    batch_sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    # Per-device param residency: with tp>1 the model-sharded leaves occupy
    # 1/tp of their bytes on each chip — the property that lets T5-XL fit
    # where replication cannot (VERDICT r2 missing 3).  Reported so tests and
    # users can verify the shrink actually happened.
    leaves = jax.tree_util.tree_leaves(params)
    params_bytes_total = int(sum(x.nbytes for x in leaves))
    params_bytes_per_device = int(
        sum(
            x.addressable_shards[0].data.nbytes
            if getattr(x, "addressable_shards", None)
            else x.nbytes
            for x in leaves
        )
    )

    # -- steps --------------------------------------------------------------
    def loss_from_batch(p, batch, dropout_rng):
        labels = batch["labels"]
        dec_in = shift_right(labels, start_id, pad_id)
        dec_mask = (dec_in != pad_id).astype(jnp.int32).at[:, 0].set(1)
        logits = model.apply(
            {"params": p},
            batch["input_ids"],
            batch["attention_mask"],
            dec_in,
            decoder_attention_mask=dec_mask,
            deterministic=dropout_rng is None,
            rngs=None if dropout_rng is None else {"dropout": dropout_rng},
        )
        return cross_entropy_loss(logits, labels, pad_id)

    from functools import partial

    import optax

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, batch, rng):
        rng, sub = jax.random.split(rng)

        def lf(pp):
            loss, _ = loss_from_batch(pp, batch, sub)
            return loss

        loss, grads = jax.value_and_grad(lf)(p)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, o, loss, rng

    @jax.jit
    def eval_step(p, batch):
        loss, ntok = loss_from_batch(p, batch, None)
        return loss, ntok

    multihost = jax.process_count() > 1

    def put_batch(b):
        if multihost:
            # every host iterates the same global batch (broadcast dataset);
            # the callback hands each host's devices their slice — device_put
            # rejects shardings with non-addressable devices
            out = {}
            for k, v in b.items():
                xa = np.asarray(v)
                out[k] = jax.make_array_from_callback(
                    xa.shape, batch_sharding, lambda idx, _v=xa: _v[idx]
                )
            return out
        return {k: jax.device_put(jnp.asarray(v), batch_sharding) for k, v in b.items()}

    if multihost:
        # a host-local key is committed to a local device and may not mix
        # with global-mesh arrays in one jit — build a replicated global key
        # (identical bits on every host: same seed)
        key_np = np.asarray(jax.random.PRNGKey(args.seed + 1))
        rng = jax.make_array_from_callback(
            key_np.shape, rep, lambda idx: key_np[idx]
        )
    else:
        rng = jax.device_put(jax.random.PRNGKey(args.seed + 1), rep)

    # -- epochs -------------------------------------------------------------
    for epoch in range(int(args.num_train_epochs)):
        t0 = time.time()
        tokens = 0
        losses = []
        nsteps = 0
        for batch_df in train_ds.iter_batches(
            batch_size=global_bs, batch_format="pandas", drop_last=True
        ):
            if len(batch_df) < global_bs:
                continue
            batch = put_batch(collate(batch_df, keys, seq_len))
            params, opt_state, loss, rng = train_step(params, opt_state, batch, rng)
            losses.append(loss)
            tokens += global_bs * seq_len
            nsteps += 1
            if args.max_steps_per_epoch and nsteps >= args.max_steps_per_epoch:
                break
        train_loss = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
        dt = time.time() - t0
        metrics: Dict[str, Any] = {
            "epoch": epoch + 1,
            "loss": train_loss,
            "steps": nsteps,
            "train_tokens_per_sec": tokens / dt if dt > 0 else 0.0,
            "train_tokens_per_sec_per_chip": (tokens / dt / ndev) if dt > 0 else 0.0,
            "mesh_data": dp,
            "mesh_model": tp,
            # how many PROCESSES the mesh spans — the cross-host proof for
            # the SPMD-multihost path (1 on a single host)
            "mesh_num_hosts": len(
                {getattr(d, "process_index", 0) for d in mesh.devices.flat}
            ),
            "params_bytes_total": params_bytes_total,
            "params_bytes_per_device": params_bytes_per_device,
        }

        if eval_ds is not None and args.evaluation_strategy == "epoch":
            parts = []  # device scalars; host sync deferred past the loop
            ebs = args.per_device_eval_batch_size * dp
            for batch_df in eval_ds.iter_batches(
                batch_size=ebs, batch_format="pandas", drop_last=False
            ):
                if len(batch_df) < ebs:  # pad partial batch with pad rows
                    reps = ebs - len(batch_df)
                    import pandas as pd

                    pad_rows = pd.concat([batch_df.iloc[-1:]] * reps, ignore_index=True)
                    for k in keys:
                        pad_rows[k] = pad_rows[k].map(
                            lambda v: np.full_like(np.asarray(v), pad_id)
                        )
                    batch_df = pd.concat([batch_df, pad_rows], ignore_index=True)
                parts.append(
                    eval_step(params, put_batch(collate(batch_df, keys, seq_len)))
                )
            # one post-loop sync keeps eval dispatch pipelined (airlint JX004)
            tot = sum(float(loss) * int(ntok) for loss, ntok in parts)  # airlint: disable=JX004 — epoch cadence, not the step path
            cnt = sum(int(ntok) for _, ntok in parts)  # airlint: disable=JX004 — epoch cadence, not the step path
            metrics["eval_loss"] = tot / max(cnt, 1)

        ckpt = None
        if args.save_strategy == "epoch":
            ckpt = Checkpoint.from_model(
                model_config=model_config,
                params=params,
                tokenizer=tokenizer,
                preprocessor=preprocessor,
                metrics=metrics,
            )
        session.report(metrics, checkpoint=ckpt)


class T5Trainer(BaseTrainer):
    """Drop-in for the reference's HuggingFaceTrainer-on-T5 configuration
    (Model_finetuning…ipynb:cc-40; flan-t5-batch-inference.py:96-111)."""

    _name_prefix = "T5Trainer"

    def __init__(
        self,
        *,
        model_config=None,
        model_name: Optional[str] = None,
        training_args: Optional[TrainingArguments] = None,
        tokenizer=None,
        pretrained_params=None,
        trainer_init_config: Optional[Dict[str, Any]] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if model_config is None:
            from tpu_air.models.t5 import T5Config

            model_config = T5Config.from_name(model_name or "flan-t5-base")
        self.model_config = model_config
        self.training_args = training_args or TrainingArguments()
        self.tokenizer = tokenizer
        self.pretrained_params = pretrained_params
        self.trainer_init_config = trainer_init_config or {}

    def _training_fn(self):
        return t5_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        cfg = dict(self.trainer_init_config)
        cfg["model_config"] = self.model_config
        cfg["training_args"] = self.training_args
        cfg["tokenizer"] = self.tokenizer
        if self.pretrained_params is not None:
            cfg["pretrained_params"] = self.pretrained_params
        return cfg
