"""tpu_air.train — trainers, configs, checkpoints, session (L3)."""

from . import session
from .checkpoint import Checkpoint
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .gbdt_trainer import GBDTTrainer, XGBoostTrainer
from .result import Result
from .session import get_dataset_shard, get_session, report
from .lm_trainer import LMTrainer, lm_train_loop
from .segformer_trainer import SegformerTrainer, segformer_train_loop
from .t5_trainer import T5Trainer, TrainingArguments, t5_train_loop
from .trainer import BaseTrainer, JaxTrainer

__all__ = [
    "BaseTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "GBDTTrainer",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "LMTrainer",
    "SegformerTrainer",
    "T5Trainer",
    "TrainingArguments",
    "XGBoostTrainer",
    "get_dataset_shard",
    "get_session",
    "report",
    "session",
    "segformer_train_loop",
    "t5_train_loop",
]
