"""Sequence-parallel (long-context) training step over a (data, sequence)
mesh.

The step runs entirely inside shard_map: activations are sequence-sharded
(each device holds L/P tokens of its batch rows), attention is ring
attention (ops/ring_attention.py — K/V rotate over the ``sequence`` axis via
ppermute/ICI), RoPE and the causal mask use global positions derived from
the shard index, and the loss/grad reductions psum over BOTH axes so the
replicated parameters take an identical update everywhere.

This is the all-to-all-free long-context recipe: context length scales
linearly with the ``sequence`` mesh axis while per-device attention memory
stays O((L/P)^2) and gradient sync stays a single psum — the capability the
reference caps at 512 tokens (NLP_workloads/Anyscale_job/utils.py:23-28).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_air.models.lm import (
    CausalLM,
    LMConfig,
    head_weight,
    lm_chunked_loss_with_targets,
)
from tpu_air.parallel.mesh import make_mesh, visible_devices
from tpu_air.parallel.shardmap_compat import shard_map_unchecked as _shard_map


def make_sp_mesh(n_devices: int = None, dp: int = None, sp: int = None) -> Mesh:
    """(data, sequence) mesh over this process's VISIBLE (lease-aware)
    devices — a chip-leased trial builds its sub-mesh, never the whole slice.
    Default sp: the largest divisor of the device count that is <= 4."""
    devs = visible_devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if sp is None:
        sp = max(d for d in range(1, min(4, n) + 1) if n % d == 0)
    if dp is None:
        if n % sp != 0:
            raise ValueError(f"sp={sp} does not divide {n} devices")
        dp = n // sp
    return make_mesh(("data", "sequence"), (dp, sp), devices=devs)


def shift_targets(input_ids: jax.Array, pad_token_id: int) -> jax.Array:
    """GLOBAL next-token shift, done before sharding: position i's target is
    token i+1 (final position gets pad → masked), so a sequence-sharded loss
    never needs its neighbor's first token."""
    return jnp.concatenate(
        [input_ids[:, 1:],
         jnp.full((input_ids.shape[0], 1), pad_token_id, input_ids.dtype)],
        axis=1,
    )


def sp_local_loss(model, params, input_ids, targets, seq_axis: str = "sequence"):
    """The per-shard unnormalized loss every SP consumer shares (train step
    and eval): global RoPE positions from the shard index, hidden states via
    ``return_hidden``, and the CHUNKED lm-head CE
    (lm_chunked_loss_with_targets) so the local (B, L/P, V) logits never
    materialize — blockwise attention fixes one long-context memory cliff,
    this fixes the other.  Returns local (sum, count)."""
    li = input_ids.shape[1]  # local shard length
    offset = jax.lax.axis_index(seq_axis) * li
    positions = jnp.broadcast_to(
        offset + jnp.arange(li, dtype=jnp.int32), input_ids.shape
    )
    hidden = model.apply({"params": params}, input_ids, positions,
                         return_hidden=True)
    return lm_chunked_loss_with_targets(
        hidden, head_weight(params, model.config), targets,
        model.config.pad_token_id,
    )


def make_sp_train_step(
    config: LMConfig,
    mesh: Mesh,
    tx: optax.GradientTransformation,
    data_axis: str = "data",
    seq_axis: str = "sequence",
):
    """Returns (jitted_step, model).  ``jitted_step(params, opt_state,
    input_ids, targets) -> (params, opt_state, loss)`` with input_ids /
    targets sharded P(data, sequence) and params/opt_state replicated."""
    cfg = LMConfig.from_dict({**config.to_dict(),
                              "attention": "ring", "sequence_axis": seq_axis})
    model = CausalLM(cfg)

    def local_step(params, opt_state, input_ids, targets):
        # Differentiate the LOCAL unnormalized loss and reduce outside the
        # grad: putting psum inside loss_fn is wrong under shard_map's
        # unchecked-replication mode, where psum's transpose psums the
        # cotangent again (a P-factor error).  loss = S_total / C_total with
        # C independent of params, so grad = psum(dS_local) / C_total.
        def loss_fn(p):
            return sp_local_loss(model, p, input_ids, targets, seq_axis)

        (s_local, c_local), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        c_total = jnp.maximum(jax.lax.psum(c_local, (data_axis, seq_axis)), 1.0)
        loss = jax.lax.psum(s_local, (data_axis, seq_axis)) / c_total
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, (data_axis, seq_axis)) / c_total, grads
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    repl, dsh = P(), P(data_axis, seq_axis)
    step = _shard_map(
        local_step, mesh=mesh,
        in_specs=(repl, repl, dsh, dsh),
        out_specs=(repl, repl, repl),
    )
    return jax.jit(step, donate_argnums=(0, 1)), model


def init_sp_params(config: LMConfig, mesh: Mesh, seed: int = 0):
    """Replicated param init (single-device trace; placed replicated)."""
    model = CausalLM(LMConfig.from_dict({**config.to_dict(), "attention": "dense",
                                         "sequence_axis": None}))
    rng = jax.random.PRNGKey(seed)
    params = model.init(rng, jnp.ones((1, 8), jnp.int32))["params"]
    return jax.device_put(params, NamedSharding(mesh, P()))


def shard_batch(mesh: Mesh, input_ids, targets, data_axis="data", seq_axis="sequence"):
    sh = NamedSharding(mesh, P(data_axis, seq_axis))
    return jax.device_put(input_ids, sh), jax.device_put(targets, sh)
