"""Parameter partitioning rules (DP / TP as config choices).

The reference's only training parallelism is DP (SURVEY.md §2C); pjit makes a
``model`` (tensor-parallel) axis nearly free, so the T5 param tree carries
path-based partition rules: MLP and attention-head matmuls shard over the
``model`` axis, everything else replicates.  With tp=1 every spec collapses
to replication and this is pure DP.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def t5_param_spec(path_names, leaf) -> P:
    """PartitionSpec for one T5 param, by its tree path."""
    names = [str(p) for p in path_names]
    leafname = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    grand = names[-3] if len(names) >= 3 else ""
    if leafname == "kernel":
        if parent in ("wi", "wi_0", "wi_1"):
            return P(None, "model")           # [d_model, d_ff]
        if parent == "wo":
            return P("model", None)           # [d_ff, d_model]
        if parent in ("q", "k", "v"):
            return P(None, "model", None)     # [d_model, heads, d_kv]
        if parent == "o":
            return P("model", None, None)     # [heads, d_kv, d_model]
        if parent == "lm_head":
            return P(None, "model")           # [d_model, vocab]
    return P()  # embeddings, norms, rel-bias: replicated


def _path_names(path) -> list:
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(p.key)
        elif hasattr(p, "name"):
            out.append(p.name)
        else:
            out.append(str(p))
    return out


def lm_param_spec(path_names, leaf) -> P:
    """PartitionSpec for one causal-LM param (models/lm), by tree path:
    attention q/k/v and SwiGLU gate/up shard their OUTPUT (heads / ff) dim
    over ``model``; o/down shard their INPUT dim; embeddings and norms
    replicate (the tied head reads the replicated embedding)."""
    names = [str(p) for p in path_names]
    leafname = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    if leafname == "kernel":
        if parent in ("q", "k", "v", "gate", "up"):
            return P(None, "model")
        if parent in ("o", "down"):
            return P("model", None)
    return P()


def _param_shardings(params, mesh, spec_fn) -> Any:
    """NamedSharding tree over ``mesh`` from a path→spec rule (axes include
    "model"; its absence → replication)."""
    has_model = "model" in mesh.axis_names

    def spec_for(path, leaf):
        if not has_model:
            return NamedSharding(mesh, P())
        spec = spec_fn(_path_names(path), leaf)
        # drop specs that don't divide evenly — XLA requires divisibility
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        ok = []
        for dim, axis in enumerate(spec):
            if axis is None:
                ok.append(None)
            elif leaf.shape[dim] % sizes.get(axis, 1) == 0:
                ok.append(axis)
            else:
                ok.append(None)
        return NamedSharding(mesh, P(*ok))

    return jax.tree_util.tree_map_with_path(spec_for, params)


def t5_param_shardings(params, mesh) -> Any:
    """NamedSharding tree for a T5 param tree over ``mesh`` (axes
    ("data","model"); "model" may be absent → replication)."""
    return _param_shardings(params, mesh, t5_param_spec)


def lm_param_shardings(params, mesh) -> Any:
    """NamedSharding tree for a causal-LM param tree (models/lm)."""
    return _param_shardings(params, mesh, lm_param_spec)


def _place(x, sharding):
    """Put one host value onto a (possibly multihost) sharding.

    ``device_put`` rejects shardings with non-addressable devices; in a
    multi-controller run every host calls this in lockstep and supplies the
    shards its local devices need via the callback."""
    if jax.process_count() > 1:
        import numpy as np

        xa = np.asarray(x)
        return jax.make_array_from_callback(
            xa.shape, sharding, lambda idx, _xa=xa: _xa[idx]
        )
    return jax.device_put(x, sharding)


def replicate(tree, mesh):
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: _place(x, sh), tree)


def shard_params(params, mesh, spec_fn=t5_param_spec):
    shardings = _param_shardings(params, mesh, spec_fn)
    return jax.tree_util.tree_map(_place, params, shardings)
