"""Device mesh construction and sub-mesh leasing.

TPU-native replacement for the reference's GPU-count resource model
(SURVEY.md §1-L0/§2B): instead of "num_gpus=1" workers coordinated by NCCL,
compute runs as SPMD programs over a `jax.sharding.Mesh`, and the scheduler
hands out *chip leases* (runtime.py) that this module turns into sub-meshes.

Axis convention (logical → physical):

* ``data``  — batch / DP axis; gradient psum rides ICI (replaces DDP
  all-reduce, Model_finetuning…ipynb:cc-29,35).
* ``model`` — tensor-parallel axis (optional; reference has none, SURVEY.md
  §2C — kept a config change away, per §7).

A process holding a chip lease (``TPU_AIR_CHIP_IDS``) sees only its leased
devices, so concurrent Tune trials / predictor actors build disjoint
sub-meshes of the same slice (§7 hard-part 1).
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _jax():
    import jax

    return jax


def leased_chip_ids() -> Optional[List[int]]:
    """Chip ids granted to this process by the scheduler, or None (all)."""
    raw = os.environ.get("TPU_AIR_CHIP_IDS")
    if not raw:
        return None
    return [int(x) for x in raw.split(",") if x != ""]


def visible_devices():
    """Devices this process may use: the leased subset, else all devices."""
    jax = _jax()
    devs = jax.devices()
    lease = leased_chip_ids()
    if lease is None:
        return list(devs)
    # Lease ids index the global device list; tolerate leases larger than the
    # local platform (CPU test meshes) by wrapping.
    n = len(devs)
    return [devs[i % n] for i in lease]


def topology() -> dict:
    """Discover the local slice topology (the ``ray.init()`` analog's first
    job on TPU — SURVEY.md §3.6)."""
    jax = _jax()
    devs = jax.devices()
    info = {
        "platform": devs[0].platform,
        "num_devices": len(devs),
        "num_visible": len(visible_devices()),
        "device_kind": getattr(devs[0], "device_kind", "unknown"),
        "process_count": jax.process_count(),
    }
    coords = getattr(devs[0], "coords", None)
    if coords is not None:
        info["coords"] = [tuple(getattr(d, "coords", ())) for d in devs]
    return info


def make_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
    devices=None,
):
    """Build a Mesh over the visible (leased) devices.

    ``shape`` may contain one ``-1`` (inferred).  Default: all devices on the
    first axis (pure DP, the reference's only training parallelism,
    SURVEY.md §2C).
    """
    jax = _jax()
    devs = list(devices) if devices is not None else visible_devices()
    n = len(devs)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    shape = list(shape)
    if -1 in shape:
        i = shape.index(-1)
        known = math.prod(s for s in shape if s != -1)
        if n % known != 0:
            raise ValueError(f"cannot infer axis: {n} devices, shape {shape}")
        shape[i] = n // known
    if math.prod(shape) != n:
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {math.prod(shape)} devices, "
            f"have {n} visible"
        )
    arr = np.array(devs).reshape(shape)
    return jax.sharding.Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None):
    devs = visible_devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} visible"
            )
        devs = devs[:num_devices]
    return make_mesh(("data",), (len(devs),), devices=devs)


def batch_sharding(mesh, axis: str = "data"):
    """NamedSharding for [batch, ...] arrays: leading dim over the data axis."""
    jax = _jax()
    P = jax.sharding.PartitionSpec
    return jax.sharding.NamedSharding(mesh, P(axis))


def replicated_sharding(mesh):
    jax = _jax()
    P = jax.sharding.PartitionSpec
    return jax.sharding.NamedSharding(mesh, P())
