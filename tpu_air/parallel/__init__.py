"""tpu_air.parallel — meshes, sub-mesh leases, host collectives (L6 comm)."""

from .collectives import Barrier, allreduce, broadcast
from .mesh import (
    batch_sharding,
    data_parallel_mesh,
    leased_chip_ids,
    make_mesh,
    replicated_sharding,
    topology,
    visible_devices,
)

__all__ = [
    "Barrier",
    "allreduce",
    "batch_sharding",
    "broadcast",
    "data_parallel_mesh",
    "leased_chip_ids",
    "make_mesh",
    "replicated_sharding",
    "topology",
    "visible_devices",
]
