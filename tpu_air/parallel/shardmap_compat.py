"""shard_map version-compat shim, shared by every mapped-kernel caller
(ring attention, sequence-parallel step): jax moved shard_map out of
experimental (>=0.7) and renamed check_rep -> check_vma."""

from __future__ import annotations


def shard_map_unchecked(fn, **kw):
    """shard_map with replication checking off (pallas_call outputs don't
    carry vma metadata yet)."""
    try:
        from jax import shard_map as sm  # jax >= 0.7
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, check_vma=False, **kw)
    except TypeError:  # pragma: no cover - older spelling
        return sm(fn, check_rep=False, **kw)
