"""Multi-host runtime: jax.distributed rendezvous + per-host agents.

The reference runs real multi-node clusters (Install_locally.md:58-64;
flan-t5-batch-inference-job-setup.yml:2-3 hands the job a managed multi-node
compute config).  The TPU-native shape of that is JAX's multi-controller
SPMD: every host of a pod slice runs the SAME program; host 0 additionally
runs the user's driver code.  This module owns:

* **rendezvous** — `ensure_initialized()` joins the cluster-wide coordination
  service (`jax.distributed.initialize`) from env or explicit args; after it,
  `jax.devices()` is the GLOBAL device list and pjit programs span hosts, ICI
  collectives intra-slice and DCN across slices (SURVEY.md §2D).
* **per-host agents** — host 0 cannot call remote Python on other hosts via
  XLA; it ships *programs*.  `HostAgentServer` (driver) + `agent_loop`
  (non-zero hosts) form the control plane: cloudpickled thunks broadcast over
  a socket, executed lockstep on every host — exactly how the SPMD train step
  launches everywhere (SURVEY.md §3.6, §7 hard-part 3).
* **local emulation** — `spawn_local_cluster()` forks N processes with
  `xla_force_host_platform_device_count` CPU devices each, so multi-host
  tests run on one machine with zero TPUs (SURVEY.md §4.3's "multi-node
  without a cluster" technique).

Env contract (set by the pod launcher / job YAML):
    TPU_AIR_COORDINATOR   host:port of process 0 (jax coordination service)
    TPU_AIR_NUM_PROCESSES world size (one per host)
    TPU_AIR_PROCESS_ID    this host's rank
    TPU_AIR_CONTROL       host:port of the agent control plane (driver side)
"""

from __future__ import annotations

import multiprocessing.connection as mpc
import os
import re
import secrets
import socket
import subprocess
import sys
import threading
import time
import traceback
from typing import Any, Callable, List, Optional

def _authkey() -> bytes:
    """Per-cluster control-plane authkey.  The launcher generates a random
    key and distributes it via the job env contract (TPU_AIR_AUTHKEY); a
    compiled-in constant would be remote code execution for anyone who can
    reach a non-loopback HostAgentServer.  The static fallback only covers
    single-host loopback emulation with no launcher."""
    key = os.environ.get("TPU_AIR_AUTHKEY")
    return key.encode() if key else b"tpu_air-local-loopback"


def _routable_host(toward: Optional[str]) -> str:
    """The local address other hosts can reach us at: the source address of
    a route toward the coordinator/GCS.  Stays 127.0.0.1 in single-host
    emulation (where the coordinator itself is loopback)."""
    target = (toward or "").split(":")[0] or "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((target, 1))  # no packets sent; just picks a route
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


_initialized = False
_ACTIVE_CLUSTER: Optional["LocalCluster"] = None


def set_active_cluster(cluster) -> None:
    """Register the cluster handle trainers use for SPMD-multihost fits
    (docs/MULTIHOST.md §3: leases spanning hosts run through the agent
    plane).  ``spawn_local_cluster`` registers automatically."""
    global _ACTIVE_CLUSTER
    _ACTIVE_CLUSTER = cluster


def active_cluster():
    return _ACTIVE_CLUSTER


# --------------------------------------------------------------------------
# rendezvous
# --------------------------------------------------------------------------


def ensure_initialized(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the jax.distributed cluster if configured; returns True when this
    process is part of a multi-process run.  Idempotent.  Reads the env
    contract when args are omitted — `tpu_air.init()` calls this first so a
    job YAML env block is all a multi-host launch needs."""
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("TPU_AIR_COORDINATOR")
    num_processes = num_processes or _env_int("TPU_AIR_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("TPU_AIR_PROCESS_ID")
    if not coordinator or not num_processes or num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id or 0,
    )
    _initialized = True
    return True


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    return int(raw) if raw else None


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    return process_index() == 0


# --------------------------------------------------------------------------
# control plane: program broadcast from host 0
# --------------------------------------------------------------------------


class HostAgentServer:
    """Driver-side (host 0) control plane.

    Accepts one connection per non-zero host, then `run(fn)` broadcasts a
    cloudpickled zero-arg thunk, executes it locally too (multi-controller
    SPMD requires every process to enter the same computation), and gathers
    per-host results.  Exceptions on any host propagate with their remote
    traceback."""

    def __init__(self, num_processes: int, address: Optional[tuple] = None):
        self.num_processes = num_processes
        addr = address or ("127.0.0.1", 0)
        self._listener = mpc.Listener(addr, authkey=_authkey())
        self.address = self._listener.address
        self._conns: dict[int, Any] = {}

    def wait_for_agents(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while len(self._conns) < self.num_processes - 1:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(self._conns)}/{self.num_processes - 1} host "
                    "agents connected"
                )
            conn = self._listener.accept()  # blocks; launcher enforces timeout
            pid = conn.recv()  # handshake: agent sends its process_id
            self._conns[int(pid)] = conn

    def run(self, fn: Callable[[], Any]) -> List[Any]:
        """Execute ``fn`` on every host (including this one); returns results
        ordered by process id."""
        import cloudpickle

        payload = cloudpickle.dumps(fn)
        for conn in self._conns.values():
            conn.send(("run", payload))
        local = _call_guarded(fn)
        results: dict[int, Any] = {0: local}
        for pid, conn in self._conns.items():
            results[pid] = conn.recv()
        out = []
        for pid in range(self.num_processes):
            status, value = results[pid]
            if status == "err":
                raise RuntimeError(f"host {pid} failed:\n{value}")
            out.append(value)
        return out

    def barrier(self) -> None:
        self.run(lambda: None)

    def shutdown(self) -> None:
        for conn in self._conns.values():
            try:
                conn.send(("exit", None))
                conn.close()
            except OSError:
                pass
        self._listener.close()


def _call_guarded(fn):
    try:
        return ("ok", fn())
    except BaseException:  # noqa: BLE001 - control-plane boundary
        return ("err", traceback.format_exc())


def agent_loop(control_address, process_id: int) -> None:
    """Non-zero hosts: connect to host 0 and execute broadcast programs in
    lockstep until told to exit."""
    import cloudpickle

    conn = mpc.Client(tuple(control_address) if isinstance(control_address, list)
                      else control_address, authkey=_authkey())
    conn.send(process_id)
    while True:
        kind, payload = conn.recv()
        if kind == "exit":
            return
        fn = cloudpickle.loads(payload)
        conn.send(_call_guarded(fn))


# --------------------------------------------------------------------------
# cross-host object plane
# --------------------------------------------------------------------------


class ObjectPlane:
    """Cross-host object fetch over the control plane (MULTIHOST.md §5).

    Each host serves its local ObjectStore on a socket and advertises the
    endpoint in the GCS KV (``objplane/<node_id>``); ``fetch`` resolves an
    object's holders through the GCS object directory, pulls the serialized
    value from one of them, and caches it in the local store — mirroring the
    reference stack's raylet-to-raylet transfer with its "zero copy is not
    guaranteed" cross-node caveat (Scaling_batch_inference.ipynb:cc-87-88)."""

    def __init__(self, store, node_id: str, gcs_address: str):
        from tpu_air.control import GcsClient

        self.store = store
        self.node_id = node_id
        self.gcs = GcsClient(gcs_address)
        # Advertise an address other hosts can actually reach: bind the
        # interface that routes toward the GCS (loopback only when the GCS
        # itself is loopback, i.e. single-host emulation) — advertising
        # 127.0.0.1 cluster-wide would make every remote fetch a KeyError.
        bind_host = _routable_host(gcs_address)
        self._listener = mpc.Listener((bind_host, 0), authkey=_authkey())
        host, port = self._listener.address
        self.address = f"{host}:{port}"
        self.gcs.kv_put(f"objplane/{node_id}", self.address.encode())
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # -- owner side ---------------------------------------------------------
    def put(self, value, object_id: Optional[str] = None) -> str:
        """Store locally and publish the location to the GCS directory."""
        ref = self.store.put(value, object_id)
        oid = getattr(ref, "id", object_id)
        self.gcs.publish_object(oid, self.node_id)
        return oid

    def _serve(self) -> None:
        from tpu_air.core import serialization

        # airlint: disable=CC001 — GIL-atomic stop flag; close() also
        # closes the listener, so a blocked accept() exits via OSError
        while not self._stop:
            try:
                conn = self._listener.accept()
            except OSError:
                return

            def handle(c):
                try:
                    while True:
                        object_id = c.recv()
                        if object_id is None:
                            return
                        if self.store.contains(object_id):
                            c.send(serialization.dumps(self.store.get(object_id)))
                        else:
                            c.send(None)
                except (EOFError, OSError):
                    pass
                finally:
                    c.close()

            threading.Thread(target=handle, args=(conn,), daemon=True).start()

    # -- consumer side --------------------------------------------------------
    def fetch(self, object_id: str):
        """Local hit, else pull from a holder named by the GCS directory and
        cache locally."""
        from tpu_air.core import serialization

        if self.store.contains(object_id):
            return self.store.get(object_id)
        loc = self.gcs.locate_object(object_id)
        if loc is None:
            raise KeyError(f"object {object_id} not in the cluster directory")
        last_err: Optional[Exception] = None
        for node_id in loc["node_ids"]:
            if node_id == self.node_id:
                continue
            raw = self.gcs.kv_get(f"objplane/{node_id}")
            if raw is None:
                continue
            host, port = raw.decode().rsplit(":", 1)
            try:
                conn = mpc.Client((host, int(port)), authkey=_authkey())
                conn.send(object_id)
                blob = conn.recv()
                conn.send(None)
                conn.close()
            except (OSError, EOFError) as e:  # holder died — try the next one
                last_err = e
                continue
            if blob is not None:
                value = serialization.loads(blob)
                try:  # cache for later readers on this host
                    self.store.put(value, object_id)
                    self.gcs.publish_object(object_id, self.node_id)
                except Exception:  # noqa: BLE001 — cache write is best-effort; value is in hand
                    pass
                return value
        raise KeyError(
            f"object {object_id} unreachable from {loc['node_ids']}: {last_err}"
        )

    def close(self) -> None:
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass
        self.gcs.close()


# --------------------------------------------------------------------------
# local multi-process emulation (tests / single machine)
# --------------------------------------------------------------------------

_AGENT_MAIN = """\
import os, sys
from tpu_air.parallel import distributed as D
pid = int(os.environ["TPU_AIR_PROCESS_ID"])
gcs = os.environ.get("TPU_AIR_GCS")
if gcs:
    # register with the C++ control plane + heartbeat (failure detection)
    try:
        from tpu_air.control import GcsClient, HeartbeatThread
        ctrl = os.environ.get("TPU_AIR_CONTROL", "")
        GcsClient(gcs).register_node(f"host-{pid}", address=ctrl)
        HeartbeatThread(gcs, f"host-{pid}", interval=0.5, node_address=ctrl).start()
    except Exception as e:
        print(f"agent {pid}: gcs registration failed: {e}", file=sys.stderr)
D.ensure_initialized()
host, port = os.environ["TPU_AIR_CONTROL"].rsplit(":", 1)
D.agent_loop((host, int(port)), pid)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalCluster:
    """N-process virtual cluster on one machine: process 0 is the caller's
    subprocess-free *driver script*; use `spawn_local_cluster` from a fresh
    process whose jax is not yet initialized."""

    def __init__(self, server: HostAgentServer, procs: List[subprocess.Popen],
                 gcs_proc: Optional[subprocess.Popen] = None,
                 gcs_address: Optional[str] = None,
                 heartbeat: Optional[Any] = None,
                 devices_per_process: int = 0):
        self.server = server
        self.procs = procs
        self.gcs_proc = gcs_proc
        self.gcs_address = gcs_address
        self.num_processes = server.num_processes
        self.devices_per_process = devices_per_process
        self._heartbeat = heartbeat
        self._gcs_client = None

    def run(self, fn):
        return self.server.run(fn)

    def nodes(self) -> list:
        """Cluster membership from the C++ control plane (alive = heartbeat
        fresh) — the failure-detection view.  Best-effort like the rest of
        the GCS wiring: a dead daemon degrades to []."""
        if self.gcs_address is None:
            return []
        try:
            if self._gcs_client is None:
                from tpu_air.control import GcsClient

                self._gcs_client = GcsClient(self.gcs_address)
            return self._gcs_client.list_nodes()
        except (ConnectionError, OSError, RuntimeError):
            self._gcs_client = None
            return []

    def shutdown(self):
        if active_cluster() is self:
            set_active_cluster(None)
        self.server.shutdown()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._gcs_client is not None:
            self._gcs_client.close()
        if self.gcs_proc is not None:
            self.gcs_proc.kill()
        # a later init() in this process must not try to join the dead daemon
        if os.environ.get("TPU_AIR_GCS") == self.gcs_address:
            os.environ.pop("TPU_AIR_GCS", None)


def spawn_local_cluster(
    num_processes: int, devices_per_process: int = 4, timeout: float = 120.0
) -> LocalCluster:
    """Start a local multi-host emulation: this process becomes host 0 of a
    ``num_processes``-process jax.distributed cluster with
    ``devices_per_process`` virtual CPU devices each; the other hosts run
    `agent_loop` in subprocesses.  Must be called before jax is imported
    (the XLA device-count flag binds at backend init)."""
    if "jax" in sys.modules and getattr(sys.modules["jax"], "_tpu_air_probe", None):
        pass  # best-effort; callers use a fresh process anyway
    coord_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"

    # C++ control plane: membership + heartbeats for the virtual hosts.
    # Best-effort — a missing protobuf toolchain degrades to no GCS.
    gcs_proc, gcs_address = None, None
    try:
        from tpu_air.control import GcsClient, HeartbeatThread, start_gcs

        gcs_proc, gcs_port = start_gcs(dead_after_ms=3000)
        gcs_address = f"127.0.0.1:{gcs_port}"
    except Exception as e:  # noqa: BLE001 — degrade to no control plane (e.g. no protoc)
        print(f"spawn_local_cluster: no gcs ({e})", file=sys.stderr)

    # per-cluster random control-plane key (see _authkey): must land in OUR
    # env BEFORE HostAgentServer binds its listener so driver and agents agree
    os.environ.setdefault("TPU_AIR_AUTHKEY", secrets.token_hex(16))

    server = HostAgentServer(num_processes)
    host, port = server.address

    env_base = dict(os.environ)
    env_base.pop("PALLAS_AXON_POOL_IPS", None)  # never let agents touch the TPU tunnel
    # strip ANY inherited device-count flag (not just the test default of 8) —
    # two conflicting flags in a child's XLA_FLAGS is an init-time error
    inherited_xla = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env_base.get("XLA_FLAGS", ""),
    ).strip()
    env_base.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            inherited_xla
            + f" --xla_force_host_platform_device_count={devices_per_process}"
        ).strip(),
        TPU_AIR_COORDINATOR=coordinator,
        TPU_AIR_NUM_PROCESSES=str(num_processes),
        TPU_AIR_CONTROL=f"{host}:{port}",
    )
    if gcs_address:
        env_base["TPU_AIR_GCS"] = gcs_address

    procs = []
    for pid in range(1, num_processes):
        env = dict(env_base)
        env["TPU_AIR_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _AGENT_MAIN],
                env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            )
        )

    # become host 0
    os.environ.update(
        {k: env_base[k] for k in ("JAX_PLATFORMS", "XLA_FLAGS", "TPU_AIR_COORDINATOR",
                                  "TPU_AIR_NUM_PROCESSES", "TPU_AIR_CONTROL")}
    )
    os.environ["TPU_AIR_PROCESS_ID"] = "0"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    # Global chip pool for the scheduler: every virtual device is a "chip",
    # host boundaries at devices_per_process (lease shapes —
    # docs/MULTIHOST.md §2).  A later tpu_air.init() picks these up.
    os.environ["TPU_AIR_NUM_CHIPS"] = str(num_processes * devices_per_process)
    os.environ["TPU_AIR_CHIPS_PER_HOST"] = str(devices_per_process)
    heartbeat = None
    if gcs_address:
        os.environ["TPU_AIR_GCS"] = gcs_address
        try:
            GcsClient(gcs_address).register_node("host-0", address=f"{host}:{port}")
            heartbeat = HeartbeatThread(gcs_address, "host-0", interval=0.5,
                                        node_address=f"{host}:{port}")
            heartbeat.start()
        except Exception as e:  # noqa: BLE001 — liveness is optional; cluster runs without it
            print(f"spawn_local_cluster: host-0 gcs registration failed: {e}",
                  file=sys.stderr)
    ensure_initialized()

    t = threading.Thread(target=server.wait_for_agents, kwargs={"timeout": timeout})
    t.start()
    t.join(timeout)
    if t.is_alive() or len(server._conns) < num_processes - 1:
        server._listener.close()  # unblocks the accept() so the thread exits
        for p in procs:
            p.kill()
        if gcs_proc is not None:
            gcs_proc.kill()
        if heartbeat is not None:
            heartbeat.stop()
        raise TimeoutError("host agents failed to connect")
    cluster = LocalCluster(server, procs, gcs_proc, gcs_address, heartbeat,
                           devices_per_process=devices_per_process)
    set_active_cluster(cluster)
    return cluster
