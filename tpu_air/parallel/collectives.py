"""Host-side collectives facade.

SURVEY.md §2D: the *device* gradient/activation plane needs no library — XLA
emits ICI/DCN collectives from pjit/shard_map.  But a host-side
broadcast/allreduce/barrier API must still exist for host coordination (data
shuffles, CPU trainer workers).  Single-control-domain implementation rides
the object store; the multi-host gRPC backend plugs in behind the same API.
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

from tpu_air.core import api as _api
from tpu_air.core import runtime as _rt


class Barrier:
    """Reusable N-party barrier over the object store.

    Each arrival seals a marker object; a party leaves once all N markers for
    the current generation exist.
    """

    def __init__(self, name: str, world_size: int):
        self.name = name
        self.world_size = world_size
        self.generation = 0

    def _store(self):
        ctx = _rt.current_worker()
        return ctx.store if ctx is not None else _rt.get_runtime().store

    def wait(self, rank: int, timeout: Optional[float] = 60.0):
        store = self._store()
        gen = self.generation
        store.put(True, f"barrier-{self.name}-{gen}-{rank}")
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in range(self.world_size):
            remain = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not store.wait_for(f"barrier-{self.name}-{gen}-{r}", timeout=remain):
                raise TimeoutError(
                    f"barrier {self.name} gen {gen}: rank {r} missing after {timeout}s"
                )
        self.generation += 1


def broadcast(value: Any = None, *, name: str, rank: int, src: int = 0,
              timeout: Optional[float] = 60.0) -> Any:
    """Rank ``src`` publishes ``value``; every rank returns it."""
    store = Barrier(name, 0)._store()
    key = f"bcast-{name}"
    if rank == src:
        store.put(value, key)
        return value
    if not store.wait_for(key, timeout=timeout):
        raise TimeoutError(f"broadcast {name}: src value missing after {timeout}s")
    return store.get(key)


def allreduce(value: Any, *, name: str, rank: int, world_size: int,
              reduce_fn: Callable[[List[Any]], Any] = sum,
              timeout: Optional[float] = 60.0) -> Any:
    """All ranks contribute; all ranks get ``reduce_fn(contributions)``.

    Host-plane only (metrics aggregation, shuffle coordination) — device
    gradients use ``jax.lax.psum`` inside the jitted step instead.
    """
    store = Barrier(name, 0)._store()
    store.put(value, f"ar-{name}-{rank}")
    vals = []
    deadline = None if timeout is None else time.monotonic() + timeout
    for r in range(world_size):
        remain = None if deadline is None else max(0.0, deadline - time.monotonic())
        if not store.wait_for(f"ar-{name}-{r}", timeout=remain):
            raise TimeoutError(f"allreduce {name}: rank {r} missing")
        vals.append(store.get(f"ar-{name}-{r}"))
    return reduce_fn(vals)


def gather(value: Any, *, name: str, rank: int, world_size: int, dst: int = 0,
           timeout: Optional[float] = 60.0) -> Optional[List[Any]]:
    """Every rank contributes; rank ``dst`` returns the rank-ordered list,
    all other ranks return None immediately.

    Use instead of :func:`allreduce` when only one rank consumes the result
    and the payloads are large (e.g. per-rank validation predictions): N
    ranks each reading N arrays is O(N^2) store traffic, a gather is O(N)."""
    store = Barrier(name, 0)._store()
    store.put(value, f"g-{name}-{rank}")
    if rank != dst:
        return None
    vals = []
    deadline = None if timeout is None else time.monotonic() + timeout
    for r in range(world_size):
        remain = None if deadline is None else max(0.0, deadline - time.monotonic())
        if not store.wait_for(f"g-{name}-{r}", timeout=remain):
            raise TimeoutError(f"gather {name}: rank {r} missing")
        vals.append(store.get(f"g-{name}-{r}"))
    return vals
