// tpu_air GCS daemon — C++ control-plane service (SURVEY.md §2B GCS row:
// "cluster metadata, actor directory, node membership, heartbeat/failure
// detection across hosts").
//
// Design: one acceptor + one thread per connection (control traffic is
// low-rate: registrations, heartbeats, directory lookups — the data plane
// never comes here).  All state lives in-memory behind a single mutex;
// liveness = heartbeat within --dead-after-ms.  Transport is length-prefixed
// protobuf (gcs.proto) — gRPC C++ is unavailable in this image; the framing
// is the smallest honest substitute and the schema ports to gRPC unchanged.
//
// Usage: tpu_air_gcs <port> [dead_after_ms]
//   prints "LISTENING <port>" on stdout once accepting (port 0 = ephemeral).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "gcs.pb.h"

namespace {

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct State {
  std::mutex mu;
  int64_t dead_after_ms = 10000;
  std::map<std::string, tpu_air::gcs::NodeInfo> nodes;
  std::map<std::string, tpu_air::gcs::ActorInfo> actors;   // by actor_id
  std::map<std::string, std::string> actor_names;          // name -> actor_id
  std::map<std::string, tpu_air::gcs::ObjectLocation> objects;
  std::map<std::string, std::string> kv;
};

void handle(State& st, const tpu_air::gcs::Request& req,
            tpu_air::gcs::Reply* rep) {
  using namespace tpu_air::gcs;
  std::lock_guard<std::mutex> lock(st.mu);
  rep->set_seq(req.seq());
  rep->set_ok(true);
  switch (req.op_case()) {
    case Request::kRegisterNode: {
      NodeInfo n = req.register_node();
      n.set_last_heartbeat_ms(now_ms());
      n.set_alive(true);
      st.nodes[n.node_id()] = n;
      break;
    }
    case Request::kHeartbeat: {
      auto it = st.nodes.find(req.heartbeat());
      if (it == st.nodes.end()) {
        rep->set_ok(false);
        rep->set_error("unknown node");
      } else {
        it->second.set_last_heartbeat_ms(now_ms());
      }
      break;
    }
    case Request::kListNodes: {
      int64_t cutoff = now_ms() - st.dead_after_ms;
      for (auto& [id, n] : st.nodes) {
        n.set_alive(n.last_heartbeat_ms() >= cutoff);
        *rep->add_nodes() = n;
      }
      break;
    }
    case Request::kRegisterActor: {
      const ActorInfo& a = req.register_actor();
      st.actors[a.actor_id()] = a;
      if (!a.name().empty()) st.actor_names[a.name()] = a.actor_id();
      break;
    }
    case Request::kLookupActor: {
      std::string id = req.lookup_actor();
      auto byname = st.actor_names.find(id);
      if (byname != st.actor_names.end()) id = byname->second;
      auto it = st.actors.find(id);
      if (it == st.actors.end()) {
        rep->set_found(false);
      } else {
        rep->set_found(true);
        *rep->mutable_actor() = it->second;
      }
      break;
    }
    case Request::kMarkActorDead: {
      auto it = st.actors.find(req.mark_actor_dead());
      if (it != st.actors.end()) {
        it->second.set_dead(true);
        // release the name only if it still maps to THIS actor — a live
        // replacement that re-registered the name must stay reachable
        if (!it->second.name().empty()) {
          auto nm = st.actor_names.find(it->second.name());
          if (nm != st.actor_names.end() && nm->second == it->first)
            st.actor_names.erase(nm);
        }
      }
      break;
    }
    case Request::kPublishObject: {
      const ObjectLocation& loc = req.publish_object();
      ObjectLocation& cur = st.objects[loc.object_id()];
      cur.set_object_id(loc.object_id());
      cur.set_size_bytes(loc.size_bytes());
      for (const auto& nid : loc.node_ids()) {
        bool have = false;
        for (const auto& e : cur.node_ids()) have |= (e == nid);
        if (!have) cur.add_node_ids(nid);
      }
      break;
    }
    case Request::kLocateObject: {
      auto it = st.objects.find(req.locate_object());
      rep->set_found(it != st.objects.end());
      if (it != st.objects.end()) *rep->mutable_location() = it->second;
      break;
    }
    case Request::kKvPut:
      st.kv[req.kv_put().key()] = req.kv_put().value();
      break;
    case Request::kKvGet: {
      auto it = st.kv.find(req.kv_get());
      rep->set_found(it != st.kv.end());
      if (it != st.kv.end()) rep->set_value(it->second);
      break;
    }
    case Request::kKvDel:
      st.kv.erase(req.kv_del());
      break;
    default:
      rep->set_ok(false);
      rep->set_error("empty or unknown op");
  }
}

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

void serve_conn(State* st, int fd) {
  constexpr uint32_t kMaxMsg = 64 * 1024 * 1024;
  for (;;) {
    uint32_t len_be = 0;
    if (!read_exact(fd, &len_be, 4)) break;
    uint32_t len = ntohl(len_be);
    if (len == 0 || len > kMaxMsg) break;
    std::string buf(len, '\0');
    if (!read_exact(fd, buf.data(), len)) break;
    tpu_air::gcs::Request req;
    tpu_air::gcs::Reply rep;
    if (!req.ParseFromString(buf)) {
      rep.set_ok(false);
      rep.set_error("parse error");
    } else {
      handle(*st, req, &rep);
    }
    std::string out;
    rep.SerializeToString(&out);
    uint32_t out_be = htonl((uint32_t)out.size());
    if (!write_exact(fd, &out_be, 4) || !write_exact(fd, out.data(), out.size()))
      break;
  }
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  ::signal(SIGPIPE, SIG_IGN);
  int port = argc > 1 ? std::atoi(argv[1]) : 0;
  State st;
  if (argc > 2) st.dead_after_ms = std::atoll(argv[2]);

  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (::bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(srv, 64) != 0) {
    std::perror("bind/listen");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &alen);
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  for (;;) {
    int fd = ::accept(srv, nullptr, nullptr);
    if (fd < 0) {
      // back off on persistent errors (EMFILE etc.) — a bare continue
      // would spin a core while the daemon "looks" alive
      ::usleep(10000);
      continue;
    }
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve_conn, &st, fd).detach();
  }
}
