// Concurrency hammer for the shm arena store (SURVEY.md §5 race detection:
// "run the C++ runtime's own tests under ASAN/TSAN").
//
// Two modes:
//   store_hammer threads <arena> <writers> <readers> <objs_per_writer>
//     — one process, writer+reader threads on one mapping. TSan instruments
//       every access, so this mode is the data-race detector target.
//   store_hammer procs <arena> <writers> <readers> <objs_per_writer>
//     — fork()ed writer/reader processes each arena_open()ing the file;
//       exercises the true cross-process protocol (ASan target; TSan cannot
//       see across processes).
//
// Writers: alloc → fill payload with a seed pattern → seal. Readers: poll
// lookups for every expected id; once sealed, verify the payload matches the
// pattern (catches seal/publish ordering bugs — a reader must never observe
// a sealed object with a partially-written body). Exit 0 iff every object is
// found and verifies.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

extern "C" {
int arena_create(const char* path, uint64_t capacity, uint32_t num_slots);
int arena_open(const char* path);
int arena_close(int h);
int64_t arena_alloc(int h, const uint8_t* id, uint64_t size);
int arena_seal(int h, const uint8_t* id);
int arena_lookup(int h, const uint8_t* id, uint64_t* offset, uint64_t* size);
int arena_lookup_pin(int h, const uint8_t* id, uint64_t* offset, uint64_t* size);
int arena_unpin(int h, const uint8_t* id, uint64_t offset);
int arena_delete(int h, const uint8_t* id);
uint64_t arena_live_objects(int h);
uint64_t arena_free_bytes(int h);
}

namespace {

constexpr uint32_t kIdBytes = 32;
constexpr uint64_t kObjSize = 4096;

void make_id(uint8_t* id, int writer, int obj) {
  std::memset(id, 0, kIdBytes);
  std::snprintf(reinterpret_cast<char*>(id), kIdBytes, "w%08d_o%08d", writer, obj);
}

uint8_t pattern_byte(int writer, int obj, uint64_t i) {
  return static_cast<uint8_t>((writer * 131 + obj * 31 + i) & 0xff);
}

// Map the raw file so payload reads/writes go through shared memory exactly
// the way the Python side does it (the .so only owns layout + atomics).
uint8_t* map_file(const char* path, uint64_t* len) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  *len = (uint64_t)st.st_size;
  return reinterpret_cast<uint8_t*>(mem);
}

int writer_loop(int h, uint8_t* base, int writer, int nobjs) {
  uint8_t id[kIdBytes];
  for (int o = 0; o < nobjs; ++o) {
    make_id(id, writer, o);
    int64_t off = arena_alloc(h, id, kObjSize);
    if (off < 0) {
      std::fprintf(stderr, "writer %d: alloc(%d) failed: %lld\n", writer, o,
                   (long long)off);
      return 1;
    }
    for (uint64_t i = 0; i < kObjSize; ++i)
      base[(uint64_t)off + i] = pattern_byte(writer, o, i);
    if (arena_seal(h, id) != 0) {
      std::fprintf(stderr, "writer %d: seal(%d) failed\n", writer, o);
      return 1;
    }
    // duplicate re-put must be rejected (and must not leak arena space)
    if (arena_alloc(h, id, kObjSize) != -3) {
      std::fprintf(stderr, "writer %d: duplicate alloc not rejected\n", writer);
      return 1;
    }
  }
  return 0;
}

int reader_loop(int h, const uint8_t* base, int nwriters, int nobjs) {
  uint8_t id[kIdBytes];
  int verified = 0;
  // Poll until every object is observed sealed and byte-exact.
  for (int spin = 0; verified < nwriters * nobjs && spin < 200000; ++spin) {
    verified = 0;
    for (int w = 0; w < nwriters; ++w) {
      for (int o = 0; o < nobjs; ++o) {
        make_id(id, w, o);
        uint64_t off = 0, size = 0;
        int rc = arena_lookup(h, id, &off, &size);
        if (rc != 1) continue;
        if (size != kObjSize) {
          std::fprintf(stderr, "reader: bad size %llu\n", (unsigned long long)size);
          return 1;
        }
        for (uint64_t i = 0; i < kObjSize; i += 97) {
          if (base[off + i] != pattern_byte(w, o, i)) {
            std::fprintf(stderr,
                         "reader: torn read w=%d o=%d i=%llu (sealed object "
                         "with unwritten body)\n",
                         w, o, (unsigned long long)i);
            return 1;
          }
        }
        ++verified;
      }
    }
  }
  if (verified != nwriters * nobjs) {
    std::fprintf(stderr, "reader: only %d/%d objects verified\n", verified,
                 nwriters * nobjs);
    return 1;
  }
  return 0;
}

// Ownership churn: two threads share an id space and race
// alloc/seal/pin/delete/unpin.  The invariant under test (TSan target): a
// PINNED object's bytes never change — even after arena_delete parks it in
// ZOMBIE and other threads' allocations are hungry for reusable blocks.
int churn_loop(int h, uint8_t* base, int pair, int iters, int nobjs) {
  uint8_t id[kIdBytes];
  for (int it = 0; it < iters; ++it) {
    int o = it % nobjs;
    std::memset(id, 0, kIdBytes);
    std::snprintf(reinterpret_cast<char*>(id), kIdBytes, "c%08d_o%08d", pair, o);
    int64_t aoff = arena_alloc(h, id, kObjSize);
    if (aoff >= 0) {
      for (uint64_t i = 0; i < kObjSize; ++i)
        base[(uint64_t)aoff + i] = pattern_byte(1000 + pair, o, i);
      arena_seal(h, id);  // may lose to a concurrent delete; fine
    } else if (aoff != -3) {
      std::fprintf(stderr, "churn %d: alloc failed %lld (reuse broken?)\n",
                   pair, (long long)aoff);
      return 1;
    }
    uint64_t off = 0, size = 0;
    if (arena_lookup_pin(h, id, &off, &size) == 1) {
      for (int round = 0; round < 2; ++round) {
        for (uint64_t i = 0; i < kObjSize; i += 61) {
          if (base[off + i] != pattern_byte(1000 + pair, o, i)) {
            std::fprintf(stderr,
                         "churn %d: pinned bytes changed (o=%d round=%d) — "
                         "reclamation ignored the pin\n",
                         pair, o, round);
            return 1;
          }
        }
        // first round verifies sealed; delete, then verify the ZOMBIE
        if (round == 0) arena_delete(h, id);
      }
      arena_unpin(h, id, off);
    }
  }
  return 0;
}

int run_threads(const char* path, int nwriters, int nreaders, int nobjs) {
  int h = arena_open(path);
  if (h < 0) return 2;
  uint64_t len = 0;
  uint8_t* base = map_file(path, &len);
  if (!base) return 2;

  std::atomic<int> failures{0};
  std::vector<std::thread> ts;
  for (int w = 0; w < nwriters; ++w)
    ts.emplace_back([&, w] { failures += writer_loop(h, base, w, nobjs); });
  for (int r = 0; r < nreaders; ++r)
    ts.emplace_back([&] { failures += reader_loop(h, base, nwriters, nobjs); });
  // ownership churn pairs: 2 threads per shared id space racing
  // alloc/pin/delete/unpin against the reclamation machinery
  for (int p = 0; p < nwriters; ++p)
    for (int t = 0; t < 2; ++t)
      ts.emplace_back(
          [&, p] { failures += churn_loop(h, base, p, 4 * nobjs, nobjs); });
  for (auto& t : ts) t.join();

  uint64_t live = arena_live_objects(h);
  if ((int)live != nwriters * nobjs) {
    std::fprintf(stderr, "live_objects=%llu expected %d\n",
                 (unsigned long long)live, nwriters * nobjs);
    failures += 1;
  }
  ::munmap(base, len);
  arena_close(h);
  return failures.load() ? 1 : 0;
}

// Duplicate-id race over tombstone churn: every round K threads race
// arena_alloc on the SAME id whose previous generation was just deleted
// (its tombstone sits in the probe chain, so one racer can recycle it while
// another claims the end-of-chain EMPTY slot).  Invariant: one
// arena_delete makes the id unfindable — a lookup hit after the delete
// means TWO sealed slots were installed for one id.
int run_dup(const char* path, int nthreads, int iters) {
  int h = arena_open(path);
  if (h < 0) return 2;
  uint64_t len = 0;
  uint8_t* base = map_file(path, &len);
  if (!base) return 2;

  uint8_t id[kIdBytes];
  std::memset(id, 0, kIdBytes);
  std::snprintf(reinterpret_cast<char*>(id), kIdBytes, "dup_target");
  int failures = 0, missed_rounds = 0;
  for (int it = 0; it < iters && !failures; ++it) {
    std::atomic<int> go{0}, sealed{0};
    std::vector<std::thread> ts;
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t] {
        while (!go.load(std::memory_order_acquire)) ::sched_yield();
        int64_t off = arena_alloc(h, id, kObjSize);
        if (off >= 0) {
          for (uint64_t i = 0; i < kObjSize; i += 257)
            base[(uint64_t)off + i] = pattern_byte(t, it, i);
          if (arena_seal(h, id) == 0) sealed.fetch_add(1);
        }
      });
    }
    go.store(1, std::memory_order_release);
    for (auto& t : ts) t.join();
    if (sealed.load() > 1) {
      std::fprintf(stderr, "dup: %d sealed generations in round %d\n",
                   sealed.load(), it);
      ++failures;
    }
    if (sealed.load() == 0) ++missed_rounds;  // all yielded — allowed (file
                                              // fallback), count only
    arena_delete(h, id);
    uint64_t off = 0, size = 0;
    if (arena_lookup(h, id, &off, &size) == 1) {
      std::fprintf(stderr,
                   "dup: id still findable after delete in round %d — "
                   "a duplicate slot survived\n", it);
      ++failures;
    }
  }
  if (missed_rounds)
    std::printf("dup: %d/%d rounds all-yield (fallback path)\n", missed_rounds,
                iters);
  ::munmap(base, len);
  arena_close(h);
  return failures ? 1 : 0;
}

int run_procs(const char* path, int nwriters, int nreaders, int nobjs) {
  std::vector<pid_t> pids;
  for (int w = 0; w < nwriters; ++w) {
    pid_t p = ::fork();
    if (p == 0) {
      int h = arena_open(path);
      uint64_t len = 0;
      uint8_t* base = map_file(path, &len);
      if (h < 0 || !base) _exit(2);
      _exit(writer_loop(h, base, w, nobjs));
    }
    pids.push_back(p);
  }
  for (int r = 0; r < nreaders; ++r) {
    pid_t p = ::fork();
    if (p == 0) {
      int h = arena_open(path);
      uint64_t len = 0;
      uint8_t* base = map_file(path, &len);
      if (h < 0 || !base) _exit(2);
      _exit(reader_loop(h, base, nwriters, nobjs));
    }
    pids.push_back(p);
  }
  int failures = 0;
  for (pid_t p : pids) {
    int st = 0;
    ::waitpid(p, &st, 0);
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) ++failures;
  }
  return failures ? 1 : 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 6) {
    std::fprintf(stderr,
                 "usage: %s threads|procs|dup <arena_path> <writers> <readers> "
                 "<objs_per_writer>\n"
                 "  dup mode: <writers> = racing threads, <readers> ignored, "
                 "<objs_per_writer> = rounds\n",
                 argv[0]);
    return 2;
  }
  const std::string mode = argv[1];
  const char* path = argv[2];
  int nwriters = std::atoi(argv[3]);
  int nreaders = std::atoi(argv[4]);
  int nobjs = std::atoi(argv[5]);

  ::unlink(path);
  uint64_t cap = (uint64_t)nwriters * nobjs * kObjSize * 2 + (1 << 20);
  if (arena_create(path, cap, 1 << 16) != 0) {
    std::fprintf(stderr, "arena_create failed\n");
    return 2;
  }
  int rc = mode == "threads" ? run_threads(path, nwriters, nreaders, nobjs)
           : mode == "dup"   ? run_dup(path, nwriters, nobjs)
                             : run_procs(path, nwriters, nreaders, nobjs);
  ::unlink(path);
  if (rc == 0) std::printf("hammer %s: OK\n", mode.c_str());
  return rc;
}
