"""Native (C++) runtime components — loader.

Builds lazily with the system toolchain on first import (single translation
unit, sub-second) and caches the .so next to the sources.  Everything here is
optional: importers must catch ImportError/OSError and fall back to the pure-
Python paths, so environments without a compiler still work.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libtpu_air_store.so")


def _ensure_built() -> str:
    src = os.path.join(_DIR, "store.cpp")
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(src):
        subprocess.run(
            ["sh", os.path.join(_DIR, "build.sh")],
            check=True,
            capture_output=True,
            timeout=120,
        )
    return _SO


def load_store_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(_ensure_built())
    lib.arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.arena_create.restype = ctypes.c_int
    lib.arena_open.argtypes = [ctypes.c_char_p]
    lib.arena_open.restype = ctypes.c_int
    lib.arena_close.argtypes = [ctypes.c_int]
    lib.arena_close.restype = ctypes.c_int
    lib.arena_alloc.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
    lib.arena_alloc.restype = ctypes.c_int64
    lib.arena_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.arena_seal.restype = ctypes.c_int
    lib.arena_lookup.argtypes = [
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.arena_lookup.restype = ctypes.c_int
    lib.arena_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.arena_delete.restype = ctypes.c_int
    lib.arena_lookup_pin.argtypes = lib.arena_lookup.argtypes
    lib.arena_lookup_pin.restype = ctypes.c_int
    lib.arena_unpin.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
    lib.arena_unpin.restype = ctypes.c_int
    lib.arena_pins.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.arena_pins.restype = ctypes.c_int64
    for fn in ("arena_capacity", "arena_used", "arena_live_objects",
               "arena_sealed_bytes", "arena_free_bytes", "arena_leaked_bytes"):
        f = getattr(lib, fn)
        f.argtypes = [ctypes.c_int]
        f.restype = ctypes.c_uint64
    return lib
