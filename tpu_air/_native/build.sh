#!/bin/sh
# Build the native components into this directory.
# Idempotent; skips the compile when the .so is newer than its sources.
# Atomic: compiles to a temp name and renames, so concurrent builders never
# corrupt a .so another process is loading, and a rebuild never truncates a
# library that is currently mapped (the old inode lives on).
set -e
cd "$(dirname "$0")"
if [ libtpu_air_store.so -nt store.cpp ] 2>/dev/null; then
  exit 0
fi
tmp="libtpu_air_store.so.tmp.$$"
${CXX:-g++} -std=c++17 -O2 -shared -fPIC -o "$tmp" store.cpp -lpthread
mv -f "$tmp" libtpu_air_store.so
