#!/bin/sh
# Build the native components into this directory.
#
#   build.sh            — (default) build libtpu_air_store.so, release flags
#   build.sh sanitizers — additionally build the store hammer under ASan and
#                         TSan (store_hammer_asan / store_hammer_tsan), the
#                         race-detection harness SURVEY.md §5 calls for
#
# Idempotent; skips a compile when the output is newer than its sources.
# Atomic: compiles to a temp name and renames, so concurrent builders never
# corrupt a binary another process is loading, and a rebuild never truncates
# a library that is currently mapped (the old inode lives on).
set -e
cd "$(dirname "$0")"

build() {
  # build <output> <flags-and-sources...>
  out="$1"; shift
  if [ "$out" -nt store.cpp ] && [ "$out" -nt store_hammer.cc ] 2>/dev/null; then
    return 0
  fi
  tmp="$out.tmp.$$"
  ${CXX:-g++} -std=c++17 -g "$@" -o "$tmp" -lpthread
  mv -f "$tmp" "$out"
}

build libtpu_air_store.so -O2 -shared -fPIC store.cpp

# GCS control-plane daemon (gcs.proto over framed TCP).  Built when protoc +
# protobuf dev headers exist; regenerates the C++ and Python bindings when
# the schema changes.
if command -v protoc >/dev/null 2>&1 && [ -e /usr/include/google/protobuf/message.h ]; then
  if [ ! -e gcs.pb.cc ] || [ gcs.proto -nt gcs.pb.cc ]; then
    protoc --cpp_out=. --python_out=../control gcs.proto
  fi
  if [ ! -e tpu_air_gcs ] || [ gcs_server.cpp -nt tpu_air_gcs ] || [ gcs.pb.cc -nt tpu_air_gcs ]; then
    tmp="tpu_air_gcs.tmp.$$"
    ${CXX:-g++} -std=c++17 -O2 -o "$tmp" gcs_server.cpp gcs.pb.cc \
      $(pkg-config --cflags --libs protobuf 2>/dev/null || echo -lprotobuf) -lpthread
    mv -f "$tmp" tpu_air_gcs
  fi
fi

if [ "$1" = "sanitizers" ]; then
  build store_hammer_asan -O1 -fsanitize=address -fno-omit-frame-pointer \
    store.cpp store_hammer.cc
  build store_hammer_tsan -O1 -fsanitize=thread -fno-omit-frame-pointer \
    store.cpp store_hammer.cc
fi
