// tpu_air native shared-memory object store (plasma analog, SURVEY.md §2B:
// "per-node shared-memory store; zero-copy Arrow objects" → C++ equivalent).
//
// One mmap'd arena file in /dev/shm shared by every process on the host:
//   [Header | index slots | data region]
// - Allocation is a lock-free bump allocator (fetch_add on the header cursor).
// - The index is a fixed-capacity open-addressing hash table; slot state
//   machines (EMPTY→CLAIMED→SEALED→TOMBSTONE) use C++11 atomics on the shared
//   mapping, so readers never take a lock and a reader either observes a
//   fully sealed object (acquire on state) or none.
// - Objects are immutable (Overview_of_Ray.ipynb:cc-4); delete tombstones the
//   slot but never reuses data space, so zero-copy readers in other processes
//   are never invalidated.
//
// The Python side maps the same file and does the payload memcpy itself
// (writes go straight into shared memory; reads are memoryview slices of the
// mapping — zero copies end to end). This library owns layout + atomics.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>

#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7470755F61697231ULL;  // "tpu_air1"
// Fixed-width object key. Python passes sha256(object_id) — ids of any
// length map to exactly 32 key bytes (embedded NULs fine; never strlen'd).
constexpr uint32_t kIdBytes = 32;

enum SlotState : uint32_t {
  kEmpty = 0,
  // RESERVED: slot won by a CAS but id/offset/size not yet written — probers
  // must NOT read the identity bytes (that would race the owner's memcpy).
  // The owner publishes CLAIMED with release order once the fields are in.
  kReserved = 1,
  kClaimed = 2,
  kSealed = 3,
  kTombstone = 4,
};

struct Slot {
  std::atomic<uint32_t> state;
  uint32_t probe_dist;  // reserved
  uint8_t id[kIdBytes];
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // bytes of data region
  uint64_t data_start;    // file offset of data region
  std::atomic<uint64_t> cursor;  // next free byte in data region (relative)
  uint32_t num_slots;     // power of two
  uint32_t _pad;
  std::atomic<uint64_t> live_objects;
  std::atomic<uint64_t> sealed_bytes;
};

struct Arena {
  uint8_t* base = nullptr;
  uint64_t mapped = 0;
  Header* hdr = nullptr;
  Slot* slots = nullptr;
};

constexpr int kMaxArenas = 64;
Arena g_arenas[kMaxArenas];
bool g_used[kMaxArenas] = {};
std::mutex g_handles_mu;  // guards g_used slot assignment (per-process)

uint64_t fnv1a(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdBytes; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

bool id_eq(const uint8_t* a, const uint8_t* b) {
  return std::memcmp(a, b, kIdBytes) == 0;
}

}  // namespace

extern "C" {

// Create + initialize an arena file. Returns 0 on success.
int arena_create(const char* path, uint64_t capacity, uint32_t num_slots) {
  if ((num_slots & (num_slots - 1)) != 0) return -2;  // must be pow2
  uint64_t index_bytes = uint64_t(num_slots) * sizeof(Slot);
  uint64_t data_start = (sizeof(Header) + index_bytes + 4095) & ~4095ULL;
  uint64_t total = data_start + capacity;

  int fd = ::open(path, O_RDWR | O_CREAT | O_EXCL, 0644);
  if (fd < 0) return -1;
  if (::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    ::unlink(path);
    return -3;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return -4;

  Header* hdr = reinterpret_cast<Header*>(mem);
  std::memset(mem, 0, sizeof(Header) + index_bytes);
  hdr->capacity = capacity;
  hdr->data_start = data_start;
  hdr->cursor.store(0, std::memory_order_relaxed);
  hdr->num_slots = num_slots;
  hdr->live_objects.store(0, std::memory_order_relaxed);
  hdr->sealed_bytes.store(0, std::memory_order_relaxed);
  // magic last, release: openers spin on it to know init is complete
  reinterpret_cast<std::atomic<uint64_t>*>(&hdr->magic)
      ->store(kMagic, std::memory_order_release);
  ::munmap(mem, total);
  return 0;
}

// Open an existing arena. Returns handle >= 0, or < 0 on error.
int arena_open(const char* path) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return -1;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return -2;
  }
  void* mem =
      ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return -3;
  Header* hdr = reinterpret_cast<Header*>(mem);
  if (reinterpret_cast<std::atomic<uint64_t>*>(&hdr->magic)
          ->load(std::memory_order_acquire) != kMagic) {
    ::munmap(mem, (size_t)st.st_size);
    return -4;
  }
  std::lock_guard<std::mutex> lock(g_handles_mu);
  for (int h = 0; h < kMaxArenas; ++h) {
    if (g_used[h]) continue;
    g_used[h] = true;
    g_arenas[h].base = reinterpret_cast<uint8_t*>(mem);
    g_arenas[h].mapped = (uint64_t)st.st_size;
    g_arenas[h].hdr = hdr;
    g_arenas[h].slots = reinterpret_cast<Slot*>(reinterpret_cast<uint8_t*>(mem) +
                                                sizeof(Header));
    return h;
  }
  ::munmap(mem, (size_t)st.st_size);  // handle table full — don't leak
  return -5;
}

// Unmap this process's view and free the handle for reuse. Safe while other
// mappings of the file (e.g. Python's own mmap serving zero-copy views)
// remain open.
int arena_close(int h) {
  std::lock_guard<std::mutex> lock(g_handles_mu);
  if (h < 0 || h >= kMaxArenas || !g_used[h]) return -1;
  ::munmap(g_arenas[h].base, (size_t)g_arenas[h].mapped);
  g_arenas[h] = Arena{};
  g_used[h] = false;
  return 0;
}

// Claim an index slot + bump-allocate `size` bytes for object `id`.
// Returns the absolute file offset the caller writes payload to, or:
//   -1 arena full   -2 index full   -3 duplicate id   -4 bad handle
int64_t arena_alloc(int h, const uint8_t* id, uint64_t size) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  Header* hdr = a.hdr;

  uint64_t off = hdr->cursor.fetch_add(size, std::memory_order_relaxed);
  // Best-effort rollback of the bump reservation on ANY failure path: if no
  // other allocation landed after ours, the cursor CAS restores `off`;
  // otherwise the space is abandoned (the store falls back to the file path
  // for this object anyway).  Without this, repeated re-puts of a duplicate
  // id would permanently consume arena space.
  auto rollback = [&]() {
    uint64_t expect = off + size;
    hdr->cursor.compare_exchange_strong(expect, off, std::memory_order_relaxed);
  };
  if (off + size > hdr->capacity) {
    rollback();
    return -1;
  }

  uint32_t mask = hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) {
      uint32_t expected = kEmpty;
      if (s.state.compare_exchange_strong(expected, kReserved,
                                          std::memory_order_acq_rel)) {
        std::memcpy(s.id, id, kIdBytes);
        s.offset = off;
        s.size = size;
        // release-publish the identity; only now may probers read s.id
        s.state.store(kClaimed, std::memory_order_release);
        return (int64_t)(hdr->data_start + off);
      }
      st = s.state.load(std::memory_order_acquire);  // lost race; re-read
    }
    // Identity unknown while RESERVED (owner mid-memcpy); wait, because if
    // the slot turns out to hold our id, skipping would insert a duplicate
    // further down the chain.  The spin is BOUNDED: a process killed between
    // reserve and publish leaves the slot RESERVED forever, and an unbounded
    // wait would hang every alloc whose probe chain crosses it.  After the
    // bound, treat it like a tombstone (worst case: a duplicate of an object
    // that was never published — harmless, it can never seal).
    for (int spin = 0; st == kReserved && spin < 100000; ++spin) {
      ::sched_yield();
      st = s.state.load(std::memory_order_acquire);
    }
    if (st == kReserved) continue;
    if ((st == kClaimed || st == kSealed) && id_eq(s.id, id)) {
      rollback();
      return -3;
    }
    // tombstone or other id → keep probing
  }
  rollback();
  return -2;
}

// Publish a claimed object. Returns 0, or -1 if not found/claimed.
int arena_seal(int h, const uint8_t* id) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -1;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return -1;
    if ((st == kClaimed) && id_eq(s.id, id)) {
      a.hdr->live_objects.fetch_add(1, std::memory_order_relaxed);
      a.hdr->sealed_bytes.fetch_add(s.size, std::memory_order_relaxed);
      s.state.store(kSealed, std::memory_order_release);
      return 0;
    }
    if (st == kSealed && id_eq(s.id, id)) return 0;  // idempotent
  }
  return -1;
}

// Look up a sealed object. Returns 1 (sealed; *offset/*size filled),
// 0 (unknown or still being written), or negative on bad handle.
int arena_lookup(int h, const uint8_t* id, uint64_t* offset, uint64_t* size) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return 0;
    if (st == kSealed && id_eq(s.id, id)) {
      *offset = a.hdr->data_start + s.offset;
      *size = s.size;
      return 1;
    }
    if (st == kClaimed && id_eq(s.id, id)) return 0;  // pending
    // tombstone / other id → continue
  }
  return 0;
}

// Tombstone an object. Space is NOT reclaimed (zero-copy reader safety).
int arena_delete(int h, const uint8_t* id) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return 0;
    if ((st == kSealed || st == kClaimed) && id_eq(s.id, id)) {
      if (st == kSealed) {
        a.hdr->live_objects.fetch_sub(1, std::memory_order_relaxed);
        a.hdr->sealed_bytes.fetch_sub(s.size, std::memory_order_relaxed);
      }
      s.state.store(kTombstone, std::memory_order_release);
      return 0;
    }
  }
  return 0;
}

uint64_t arena_capacity(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr) ? g_arenas[h].hdr->capacity : 0;
}

uint64_t arena_used(int h) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return 0;
  uint64_t c = g_arenas[h].hdr->cursor.load(std::memory_order_relaxed);
  uint64_t cap = g_arenas[h].hdr->capacity;
  return c < cap ? c : cap;
}

uint64_t arena_live_objects(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->live_objects.load(std::memory_order_relaxed)
             : 0;
}

uint64_t arena_sealed_bytes(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->sealed_bytes.load(std::memory_order_relaxed)
             : 0;
}

}  // extern "C"
