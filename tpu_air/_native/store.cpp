// tpu_air native shared-memory object store (plasma analog, SURVEY.md §2B:
// "per-node shared-memory store; zero-copy Arrow objects" → C++ equivalent;
// §2B core_worker row: object ownership/ref-counting in native code).
//
// One mmap'd arena file in /dev/shm shared by every process on the host:
//   [Header | free-list entries | index slots | data region]
// - Allocation first tries the shared FREE LIST (first-fit over reclaimed
//   blocks, CAS-claimed), then falls back to a lock-free bump allocator
//   (fetch_add on the header cursor).
// - The index is a fixed-capacity open-addressing hash table; slot state
//   machines (EMPTY→CLAIMED→SEALED→ZOMBIE→TOMBSTONE) use C++11 atomics on
//   the shared mapping, so readers never take a lock and a reader either
//   observes a fully sealed object (acquire on state) or none.
// - Objects are immutable (Overview_of_Ray.ipynb:cc-4).  OWNERSHIP: readers
//   that hold zero-copy views pin the object (arena_lookup_pin/arena_unpin,
//   a cross-process atomic refcount in the slot).  arena_delete on a pinned
//   object parks it in ZOMBIE: invisible to lookups, bytes intact.  The
//   LAST unpin — or delete itself when no pins are out — tombstones the
//   slot and pushes its block onto the free list for reuse.  This is the
//   plasma refcount contract: space is reclaimed exactly when no process
//   can still be reading it.
//
// The Python side maps the same file and does the payload memcpy itself
// (writes go straight into shared memory; reads are memoryview slices of the
// mapping — zero copies end to end). This library owns layout + atomics.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>

#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7470755F61697232ULL;  // "tpu_air2" (layout v2)
// Fixed-width object key. Python passes sha256(object_id) — ids of any
// length map to exactly 32 key bytes (embedded NULs fine; never strlen'd).
constexpr uint32_t kIdBytes = 32;
constexpr uint64_t kAlign = 64;        // block size granularity
constexpr uint64_t kMinFragment = 128; // smallest remainder worth re-listing
constexpr uint32_t kFreeSlots = 4096;  // shared free-list capacity
constexpr uint64_t kFreeBusy = 1;      // sentinel: entry mid-update

enum SlotState : uint32_t {
  kEmpty = 0,
  // RESERVED: slot won by a CAS but id/offset/size not yet written — probers
  // must NOT read the identity bytes (that would race the owner's memcpy).
  // The owner publishes CLAIMED with release order once the fields are in.
  kReserved = 1,
  kClaimed = 2,
  kSealed = 3,
  kTombstone = 4,
  // ZOMBIE: deleted while pinned — invisible to lookups, bytes intact until
  // the last unpin reclaims the block.
  kZombie = 5,
};

struct Slot {
  std::atomic<uint32_t> state;
  std::atomic<uint32_t> pins;  // zero-copy readers holding views (x-process)
  uint8_t id[kIdBytes];
  uint64_t offset;
  uint64_t size;   // payload bytes
  uint64_t block;  // allocated block bytes (>= size; what reclaim returns)
};

// Free-list entry lifecycle: size 0 (empty) → kFreeBusy (being written) →
// block size (available) → kFreeBusy (being claimed) → 0.  offset is only
// read/written by the entry's current owner (the thread that won the CAS).
struct FreeEntry {
  std::atomic<uint64_t> size;
  uint64_t offset;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // bytes of data region
  uint64_t data_start;    // file offset of data region
  std::atomic<uint64_t> cursor;  // next free byte in data region (relative)
  uint32_t num_slots;     // power of two
  uint32_t _pad;
  std::atomic<uint64_t> live_objects;
  std::atomic<uint64_t> sealed_bytes;
  std::atomic<uint64_t> free_bytes;    // total bytes sitting in the free list
  std::atomic<uint64_t> leaked_bytes;  // reclaimed blocks the full list dropped
};

struct Arena {
  uint8_t* base = nullptr;
  uint64_t mapped = 0;
  Header* hdr = nullptr;
  FreeEntry* freelist = nullptr;
  Slot* slots = nullptr;
};

constexpr int kMaxArenas = 64;
Arena g_arenas[kMaxArenas];
bool g_used[kMaxArenas] = {};
std::mutex g_handles_mu;  // guards g_used slot assignment (per-process)

uint64_t fnv1a(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdBytes; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

bool id_eq(const uint8_t* a, const uint8_t* b) {
  return std::memcmp(a, b, kIdBytes) == 0;
}

uint64_t round_block(uint64_t size) {
  uint64_t b = (size + kAlign - 1) & ~(kAlign - 1);
  return b ? b : kAlign;
}

// Return a reclaimed block to the shared free list.  A full list leaks the
// block (counted) rather than blocking — correctness over completeness.
void push_free(Arena& a, uint64_t offset, uint64_t block) {
  for (uint32_t i = 0; i < kFreeSlots; ++i) {
    FreeEntry& e = a.freelist[i];
    uint64_t expected = 0;
    if (e.size.load(std::memory_order_relaxed) == 0 &&
        e.size.compare_exchange_strong(expected, kFreeBusy,
                                       std::memory_order_acq_rel)) {
      e.offset = offset;
      e.size.store(block, std::memory_order_release);
      a.hdr->free_bytes.fetch_add(block, std::memory_order_relaxed);
      return;
    }
  }
  a.hdr->leaked_bytes.fetch_add(block, std::memory_order_relaxed);
}

// First-fit claim from the free list.  Returns the data-relative offset and
// sets *block_out, or UINT64_MAX when nothing fits.
uint64_t claim_free(Arena& a, uint64_t need, uint64_t* block_out) {
  for (uint32_t i = 0; i < kFreeSlots; ++i) {
    FreeEntry& e = a.freelist[i];
    uint64_t s = e.size.load(std::memory_order_acquire);
    if (s <= kFreeBusy || s < need) continue;
    if (!e.size.compare_exchange_strong(s, kFreeBusy,
                                        std::memory_order_acq_rel))
      continue;
    uint64_t off = e.offset;
    e.size.store(0, std::memory_order_release);  // entry free for reuse
    a.hdr->free_bytes.fetch_sub(s, std::memory_order_relaxed);
    if (s - need >= kMinFragment) {
      push_free(a, off + need, s - need);
      *block_out = need;
    } else {
      *block_out = s;  // absorb the fragment
    }
    return off;
  }
  return UINT64_MAX;
}


}  // namespace

extern "C" {

// Create + initialize an arena file. Returns 0 on success.
int arena_create(const char* path, uint64_t capacity, uint32_t num_slots) {
  if ((num_slots & (num_slots - 1)) != 0) return -2;  // must be pow2
  uint64_t free_bytes_region = uint64_t(kFreeSlots) * sizeof(FreeEntry);
  uint64_t index_bytes = uint64_t(num_slots) * sizeof(Slot);
  uint64_t meta = sizeof(Header) + free_bytes_region + index_bytes;
  uint64_t data_start = (meta + 4095) & ~4095ULL;
  uint64_t total = data_start + capacity;

  int fd = ::open(path, O_RDWR | O_CREAT | O_EXCL, 0644);
  if (fd < 0) return -1;
  if (::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    ::unlink(path);
    return -3;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return -4;

  Header* hdr = reinterpret_cast<Header*>(mem);
  std::memset(mem, 0, meta);
  hdr->capacity = capacity;
  hdr->data_start = data_start;
  hdr->cursor.store(0, std::memory_order_relaxed);
  hdr->num_slots = num_slots;
  hdr->live_objects.store(0, std::memory_order_relaxed);
  hdr->sealed_bytes.store(0, std::memory_order_relaxed);
  hdr->free_bytes.store(0, std::memory_order_relaxed);
  hdr->leaked_bytes.store(0, std::memory_order_relaxed);
  // magic last, release: openers spin on it to know init is complete
  reinterpret_cast<std::atomic<uint64_t>*>(&hdr->magic)
      ->store(kMagic, std::memory_order_release);
  ::munmap(mem, total);
  return 0;
}

// Open an existing arena. Returns handle >= 0, or < 0 on error.
int arena_open(const char* path) {
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return -1;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return -2;
  }
  void* mem =
      ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return -3;
  Header* hdr = reinterpret_cast<Header*>(mem);
  if (reinterpret_cast<std::atomic<uint64_t>*>(&hdr->magic)
          ->load(std::memory_order_acquire) != kMagic) {
    ::munmap(mem, (size_t)st.st_size);
    return -4;
  }
  std::lock_guard<std::mutex> lock(g_handles_mu);
  for (int h = 0; h < kMaxArenas; ++h) {
    if (g_used[h]) continue;
    g_used[h] = true;
    g_arenas[h].base = reinterpret_cast<uint8_t*>(mem);
    g_arenas[h].mapped = (uint64_t)st.st_size;
    g_arenas[h].hdr = hdr;
    g_arenas[h].freelist = reinterpret_cast<FreeEntry*>(
        reinterpret_cast<uint8_t*>(mem) + sizeof(Header));
    g_arenas[h].slots = reinterpret_cast<Slot*>(
        reinterpret_cast<uint8_t*>(mem) + sizeof(Header) +
        uint64_t(kFreeSlots) * sizeof(FreeEntry));
    return h;
  }
  ::munmap(mem, (size_t)st.st_size);  // handle table full — don't leak
  return -5;
}

// Unmap this process's view and free the handle for reuse. Safe while other
// mappings of the file (e.g. Python's own mmap serving zero-copy views)
// remain open.
int arena_close(int h) {
  std::lock_guard<std::mutex> lock(g_handles_mu);
  if (h < 0 || h >= kMaxArenas || !g_used[h]) return -1;
  ::munmap(g_arenas[h].base, (size_t)g_arenas[h].mapped);
  g_arenas[h] = Arena{};
  g_used[h] = false;
  return 0;
}

// Claim an index slot + bump-allocate `size` bytes for object `id`.
// Returns the absolute file offset the caller writes payload to, or:
//   -1 arena full   -2 index full   -3 duplicate id   -4 bad handle
int64_t arena_alloc(int h, const uint8_t* id, uint64_t size) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  Header* hdr = a.hdr;

  // Reclaimed space first (ownership/ref-counting made it safe to reuse),
  // bump allocation as the fallback.
  uint64_t need = round_block(size);
  uint64_t block = 0;
  bool from_free = true;
  uint64_t off = claim_free(a, need, &block);
  if (off == UINT64_MAX) {
    from_free = false;
    block = need;
    off = hdr->cursor.fetch_add(need, std::memory_order_relaxed);
  }
  // Undo the reservation on ANY failure path: free-list blocks go back to
  // the list; for bump blocks, if no other allocation landed after ours the
  // cursor CAS restores `off`, otherwise the space is abandoned (the store
  // falls back to the file path for this object anyway).  Without this,
  // repeated re-puts of a duplicate id would permanently consume space.
  auto rollback = [&]() {
    if (from_free) {
      push_free(a, off, block);
    } else {
      uint64_t expect = off + need;
      hdr->cursor.compare_exchange_strong(expect, off, std::memory_order_relaxed);
    }
  };
  if (!from_free && off + need > hdr->capacity) {
    rollback();
    return -1;
  }

  uint32_t mask = hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  // First TOMBSTONE seen on the probe chain: claimable once the duplicate
  // scan has reached kEmpty (without slot reuse, put/delete churn would
  // permanently exhaust the fixed-capacity index).  Zombies are NOT
  // reusable — their block is still pinned by readers.
  uint32_t tomb_idx = UINT32_MAX;
  auto install = [&](Slot& s) {
    std::memcpy(s.id, id, kIdBytes);
    s.offset = off;
    s.size = size;
    s.block = block;
    s.pins.store(0, std::memory_order_relaxed);
    // publish the identity; only now may probers read s.id.  seq_cst (not
    // just release) so the post-install verify scan below forms the SB
    // pattern with a racing writer — see `finish`.
    s.state.store(kClaimed, std::memory_order_seq_cst);
  };
  // Post-install duplicate verify.  Tombstone recycling makes the pre-claim
  // duplicate scan insufficient on its own: writer A can install id X into an
  // early tombstone AFTER writer B's scan probed past it while B claims the
  // end-of-chain EMPTY slot — two live slots for one id.  So after winning a
  // CAS each writer re-scans the chain (SB pattern: the claim is a seq_cst
  // store and these are seq_cst loads — of two racing writers at least one
  // is guaranteed to see the other's claim).  A writer that sees a rival
  // demotes ITS OWN slot and reports duplicate; worst case both yield and the
  // caller's file-store fallback keeps the object durable.
  auto finish = [&](uint32_t my_idx) -> int64_t {
    Slot& mine = a.slots[my_idx];
    uint32_t vidx = (uint32_t)(fnv1a(id)) & mask;
    for (uint32_t probe = 0; probe < hdr->num_slots; ++probe, vidx = (vidx + 1) & mask) {
      if (vidx == my_idx) continue;
      Slot& v = a.slots[vidx];
      uint32_t st = v.state.load(std::memory_order_seq_cst);
      if (st == kEmpty) break;
      for (int spin = 0; st == kReserved && spin < 100000; ++spin) {
        ::sched_yield();
        st = v.state.load(std::memory_order_acquire);
      }
      if ((st == kClaimed || st == kSealed) && id_eq(v.id, id)) {
        // CAS, not a plain store: a concurrent delete may have tombstoned
        // our claimed slot already and an alloc recycled it for another id.
        uint32_t c = kClaimed;
        mine.state.compare_exchange_strong(c, kTombstone,
                                           std::memory_order_acq_rel);
        rollback();
        return -3;
      }
    }
    return (int64_t)(hdr->data_start + off);
  };
  for (uint32_t probe = 0; probe < hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) {
      // end of chain, no duplicate: prefer recycling the earliest tombstone
      if (tomb_idx != UINT32_MAX) {
        Slot& t = a.slots[tomb_idx];
        uint32_t expected = kTombstone;
        if (t.state.compare_exchange_strong(expected, kReserved,
                                            std::memory_order_acq_rel)) {
          install(t);
          return finish(tomb_idx);
        }
        // lost the tombstone to a concurrent alloc — fall through to kEmpty
      }
      uint32_t expected = kEmpty;
      if (s.state.compare_exchange_strong(expected, kReserved,
                                          std::memory_order_acq_rel)) {
        install(s);
        return finish(idx);
      }
      st = s.state.load(std::memory_order_acquire);  // lost race; re-read
    }
    // Identity unknown while RESERVED (owner mid-memcpy); wait, because if
    // the slot turns out to hold our id, skipping would insert a duplicate
    // further down the chain.  The spin is BOUNDED: a process killed between
    // reserve and publish leaves the slot RESERVED forever, and an unbounded
    // wait would hang every alloc whose probe chain crosses it.  After the
    // bound, treat it like a tombstone (worst case: a duplicate of an object
    // that was never published — harmless, it can never seal).
    for (int spin = 0; st == kReserved && spin < 100000; ++spin) {
      ::sched_yield();
      st = s.state.load(std::memory_order_acquire);
    }
    if (st == kReserved) continue;
    if ((st == kClaimed || st == kSealed) && id_eq(s.id, id)) {
      rollback();
      return -3;
    }
    if (st == kTombstone && tomb_idx == UINT32_MAX) tomb_idx = idx;
    // zombie (incl. a deleted generation of our id) / other id → probe on
  }
  // chain never hit kEmpty (full table): a recorded tombstone still works
  if (tomb_idx != UINT32_MAX) {
    Slot& t = a.slots[tomb_idx];
    uint32_t expected = kTombstone;
    if (t.state.compare_exchange_strong(expected, kReserved,
                                        std::memory_order_acq_rel)) {
      install(t);
      return finish(tomb_idx);
    }
  }
  rollback();
  return -2;
}

// Publish a claimed object. Returns 0, or -1 if not found/claimed.
int arena_seal(int h, const uint8_t* id) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -1;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return -1;
    if ((st == kClaimed) && id_eq(s.id, id)) {
      a.hdr->live_objects.fetch_add(1, std::memory_order_relaxed);
      a.hdr->sealed_bytes.fetch_add(s.size, std::memory_order_relaxed);
      s.state.store(kSealed, std::memory_order_release);
      return 0;
    }
    if (st == kSealed && id_eq(s.id, id)) return 0;  // idempotent
  }
  return -1;
}

// Look up a sealed object. Returns 1 (sealed; *offset/*size filled),
// 0 (unknown or still being written), or negative on bad handle.
int arena_lookup(int h, const uint8_t* id, uint64_t* offset, uint64_t* size) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return 0;
    if (st == kSealed && id_eq(s.id, id)) {
      *offset = a.hdr->data_start + s.offset;
      *size = s.size;
      return 1;
    }
    if (st == kClaimed && id_eq(s.id, id)) return 0;  // pending
    // tombstone / zombie / other id → continue
  }
  return 0;
}

// Look up AND pin a sealed object: the caller owns one reference, and the
// bytes stay valid (even across arena_delete) until the matching
// arena_unpin.  Returns 1/0/negative like arena_lookup.
//
// Pin/delete race: the pin is published (seq_cst fetch_add) BEFORE the
// state re-check, and delete publishes ZOMBIE (seq_cst) BEFORE reading the
// pin count — so either the deleter observes our pin and defers
// reclamation to our unpin, or we observe its ZOMBIE and back out.
int arena_lookup_pin(int h, const uint8_t* id, uint64_t* offset, uint64_t* size) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return 0;
    if (st == kSealed && id_eq(s.id, id)) {
      s.pins.fetch_add(1, std::memory_order_seq_cst);
      if (s.state.load(std::memory_order_seq_cst) != kSealed) {
        // deleted between find and pin — undo; never resurrect a zombie.
        // NB: offset/block are captured BEFORE the tombstone CAS — the
        // instant the slot turns TOMBSTONE a concurrent alloc may recycle
        // it and overwrite those fields (TSan-verified ordering).
        if (s.pins.fetch_sub(1, std::memory_order_seq_cst) == 1 &&
            s.state.load(std::memory_order_seq_cst) == kZombie) {
          uint64_t blk_off = s.offset, blk = s.block;
          uint32_t z = kZombie;
          if (s.state.compare_exchange_strong(z, kTombstone,
                                              std::memory_order_acq_rel))
            push_free(a, blk_off, blk);
        }
        return 0;
      }
      *offset = a.hdr->data_start + s.offset;
      *size = s.size;
      return 1;
    }
    if (st == kClaimed && id_eq(s.id, id)) return 0;  // pending
  }
  return 0;
}

// Release one pin taken by arena_lookup_pin.  `offset` is the absolute
// offset that call returned — it disambiguates a re-put of the same id
// whose earlier generation is still parked in ZOMBIE.  The last unpin of a
// zombie tombstones it and returns its block to the free list.
int arena_unpin(int h, const uint8_t* id, uint64_t offset) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return -1;
    if ((st == kSealed || st == kZombie) && id_eq(s.id, id) &&
        a.hdr->data_start + s.offset == offset) {
      uint32_t prev = s.pins.fetch_sub(1, std::memory_order_seq_cst);
      if (prev == 1 && s.state.load(std::memory_order_seq_cst) == kZombie) {
        // capture before the CAS: a TOMBSTONE slot is instantly recyclable
        uint64_t blk_off = s.offset, blk = s.block;
        uint32_t z = kZombie;
        if (s.state.compare_exchange_strong(z, kTombstone,
                                            std::memory_order_acq_rel))
          push_free(a, blk_off, blk);
      }
      return 0;
    }
  }
  return -1;
}

// Delete an object.  Unpinned objects are tombstoned and their block is
// reclaimed immediately; pinned objects park in ZOMBIE (invisible, bytes
// intact) until the last reader's unpin reclaims them.
int arena_delete(int h, const uint8_t* id) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return 0;
    if (st == kClaimed && id_eq(s.id, id)) {
      // Never sealed → no readers, but the OWNER may still be memcpy'ing
      // into the block; reusing it would corrupt a future object.  Tombstone
      // without reclaim (rare path: delete of an id that never sealed).
      uint32_t c = kClaimed;
      s.state.compare_exchange_strong(c, kTombstone, std::memory_order_acq_rel);
      return 0;
    }
    if (st == kSealed && id_eq(s.id, id)) {
      uint32_t expected = kSealed;
      if (!s.state.compare_exchange_strong(expected, kZombie,
                                           std::memory_order_seq_cst))
        return 0;  // concurrent deleter won
      a.hdr->live_objects.fetch_sub(1, std::memory_order_relaxed);
      a.hdr->sealed_bytes.fetch_sub(s.size, std::memory_order_relaxed);
      if (s.pins.load(std::memory_order_seq_cst) == 0) {
        // capture before the CAS: a TOMBSTONE slot is instantly recyclable
        uint64_t blk_off = s.offset, blk = s.block;
        uint32_t z = kZombie;
        if (s.state.compare_exchange_strong(z, kTombstone,
                                            std::memory_order_acq_rel))
          push_free(a, blk_off, blk);
      }
      return 0;
    }
  }
  return 0;
}

// Current pin count (diagnostics/tests). -1 when the object is unknown.
int64_t arena_pins(int h, const uint8_t* id) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return -4;
  Arena& a = g_arenas[h];
  uint32_t mask = a.hdr->num_slots - 1;
  uint32_t idx = (uint32_t)(fnv1a(id)) & mask;
  for (uint32_t probe = 0; probe < a.hdr->num_slots; ++probe, idx = (idx + 1) & mask) {
    Slot& s = a.slots[idx];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return -1;
    if ((st == kSealed || st == kZombie) && id_eq(s.id, id))
      return (int64_t)s.pins.load(std::memory_order_relaxed);
  }
  return -1;
}

uint64_t arena_capacity(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr) ? g_arenas[h].hdr->capacity : 0;
}

uint64_t arena_used(int h) {
  if (h < 0 || h >= kMaxArenas || !g_arenas[h].hdr) return 0;
  uint64_t c = g_arenas[h].hdr->cursor.load(std::memory_order_relaxed);
  uint64_t cap = g_arenas[h].hdr->capacity;
  return c < cap ? c : cap;
}

uint64_t arena_live_objects(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->live_objects.load(std::memory_order_relaxed)
             : 0;
}

uint64_t arena_sealed_bytes(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->sealed_bytes.load(std::memory_order_relaxed)
             : 0;
}

uint64_t arena_free_bytes(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->free_bytes.load(std::memory_order_relaxed)
             : 0;
}

uint64_t arena_leaked_bytes(int h) {
  return (h >= 0 && h < kMaxArenas && g_arenas[h].hdr)
             ? g_arenas[h].hdr->leaked_bytes.load(std::memory_order_relaxed)
             : 0;
}

}  // extern "C"
