"""Block format for the data layer.

The reference's Ray Data represents a Dataset as a list of Arrow-backed
blocks in the object store (SURVEY.md §1-L2: "distributed datasets as lists
of Arrow-backed blocks"; "Backed by PyArrow", Introduction…ipynb:cc-3).  We
keep that: the canonical block is a ``pyarrow.Table``; when rows hold values
Arrow can't type (PIL images, raw tensors with object dtype), the block falls
back to a ``pandas.DataFrame`` with object columns — mirroring Ray's
simple-block fallback.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Union

import numpy as np
import pandas as pd

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None

Block = Union["pa.Table", pd.DataFrame]

#: Column name used when items are not dicts (ray.data.from_items parity).
VALUE_COLUMN = "item"


def block_from_rows(rows: Sequence[Dict[str, Any]]) -> Block:
    df = pd.DataFrame(list(rows))
    return block_from_pandas(df)


def block_from_pandas(df: pd.DataFrame) -> Block:
    if pa is not None:
        try:
            return pa.Table.from_pandas(df, preserve_index=False)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError,
                ValueError, TypeError):
            pass
    return df.reset_index(drop=True)


def block_to_pandas(block: Block) -> pd.DataFrame:
    if pa is not None and isinstance(block, pa.Table):
        return block.to_pandas()
    return block


def block_to_numpy(block: Block) -> Dict[str, np.ndarray]:
    df = block_to_pandas(block)
    out = {}
    for col in df.columns:
        vals = df[col].to_numpy()
        if vals.dtype == object and len(vals) and isinstance(vals[0], np.ndarray):
            try:
                vals = np.stack(vals)
            except ValueError:
                pass
        out[col] = vals
    return out


def block_num_rows(block: Block) -> int:
    if pa is not None and isinstance(block, pa.Table):
        return block.num_rows
    return len(block)


def block_columns(block: Block) -> List[str]:
    if pa is not None and isinstance(block, pa.Table):
        return list(block.column_names)
    return list(block.columns)


def block_slice(block: Block, start: int, stop: int) -> Block:
    if pa is not None and isinstance(block, pa.Table):
        return block.slice(start, stop - start)
    return block.iloc[start:stop].reset_index(drop=True)


def concat_blocks(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0] or list(blocks[:1])
    if pa is not None and all(isinstance(b, pa.Table) for b in blocks):
        try:
            return pa.concat_tables(blocks, promote_options="default")
        except (pa.ArrowInvalid, TypeError):
            pass
    return pd.concat(
        [block_to_pandas(b) for b in blocks], ignore_index=True
    )


def block_schema(block: Block):
    if pa is not None and isinstance(block, pa.Table):
        return block.schema
    return list(zip(block.columns, block.dtypes))


def to_batch_format(block: Block, batch_format: str):
    """Convert a block to the user-facing batch format of ``map_batches``
    (``batch_format="pandas"`` at Model_finetuning…ipynb:cc-27)."""
    if batch_format in ("pandas", "default"):
        return block_to_pandas(block)
    if batch_format == "numpy":
        return block_to_numpy(block)
    if batch_format == "pyarrow":
        if pa is not None and isinstance(block, pa.Table):
            return block
        return pa.Table.from_pandas(block_to_pandas(block), preserve_index=False)
    if batch_format == "native":
        return block
    raise ValueError(f"unknown batch_format: {batch_format!r}")


def from_batch(batch) -> Block:
    """Normalize a user-returned batch back into a block."""
    if pa is not None and isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, pd.DataFrame):
        return block_from_pandas(batch)
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if arr.ndim > 1:
                cols[k] = list(arr)  # keep multi-dim arrays as object cells
            else:
                cols[k] = v
        return block_from_pandas(pd.DataFrame(cols))
    if isinstance(batch, (list, tuple)):
        if batch and isinstance(batch[0], dict):
            return block_from_rows(batch)
        return block_from_pandas(pd.DataFrame({VALUE_COLUMN: list(batch)}))
    raise TypeError(
        f"map_batches fn must return DataFrame / dict-of-arrays / Table, got {type(batch)}"
    )
