"""Distributed Dataset: lists of blocks in the object store.

Parity surface (SURVEY.md §1-L2, exercised at the cited cells):
``map_batches`` (Scaling_model_training.ipynb:cc-33), ``limit``
(Model_finetuning…ipynb:cc-21), ``train_test_split`` (Introduction…ipynb:cc-10),
``repartition`` (cc-11), ``schema/count/show/take/to_pandas`` (cc-15-17),
``groupby(...).mean(...)`` (cc-18), ``drop_columns`` (cc-58), plus ``split``
(per-worker shards feeding the Trainer, Model_finetuning…ipynb:cc-29 figure).

Blocks live in the shared-memory object store (core layer) and are processed
in parallel by tasks or an actor pool — preprocessing stays on host CPUs;
device work enters only at the trainer/predictor boundary (SURVEY.md §7
architecture stance).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from tpu_air.core import ObjectRef, get, put, remote
from tpu_air.core.actor_pool import ActorPool

from . import block as B


class ActorPoolStrategy:
    """compute= strategy for map_batches: a fixed/autoscaling pool of actors
    (the architecture behind BatchPredictor, Scaling_batch_inference.ipynb:cc-4
    "autoscaling the actor pool")."""

    def __init__(self, size: Optional[int] = None, min_size: int = 1,
                 max_size: Optional[int] = None, num_chips: float = 0):
        self.size = size
        self.min_size = size or min_size
        self.max_size = size or max_size or max(2, self.min_size)
        self.num_chips = num_chips


def _apply_fn_to_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs):
    n = B.block_num_rows(blk)
    if n == 0:
        return blk
    step = batch_size or n
    outs = []
    for start in range(0, n, step):
        batch = B.to_batch_format(B.block_slice(blk, start, min(start + step, n)), batch_format)
        out = fn(batch, *fn_args, **fn_kwargs)
        outs.append(B.from_batch(out))
    return B.concat_blocks(outs)


@remote
def _map_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs):
    return _apply_fn_to_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs)


@remote
class _MapWorker:
    """Actor for callable-class map_batches (holds expensive state, e.g. a
    model on a leased chip)."""

    def __init__(self, fn_or_cls, constructor_args, constructor_kwargs):
        if isinstance(fn_or_cls, type):
            self.fn = fn_or_cls(*constructor_args, **constructor_kwargs)
        else:
            self.fn = fn_or_cls

    def apply(self, blk, batch_size, batch_format, fn_args, fn_kwargs):
        return _apply_fn_to_block(self.fn, blk, batch_size, batch_format, fn_args, fn_kwargs)


class Dataset:
    """A distributed dataset = ordered list of block refs."""

    def __init__(self, block_refs: List[ObjectRef]):
        self._block_refs = list(block_refs)
        self._cached_num_rows: Optional[int] = None

    # -- introspection -----------------------------------------------------
    def num_blocks(self) -> int:
        return len(self._block_refs)

    def get_internal_block_refs(self) -> List[ObjectRef]:
        return list(self._block_refs)

    def _blocks(self) -> Iterator[B.Block]:
        for ref in self._block_refs:
            yield get(ref)

    def count(self) -> int:
        if self._cached_num_rows is None:
            self._cached_num_rows = sum(B.block_num_rows(b) for b in self._blocks())
        return self._cached_num_rows

    def __len__(self) -> int:  # convenience; Ray deprecates this but HF uses len()
        return self.count()

    def schema(self):
        for b in self._blocks():
            if B.block_num_rows(b) > 0:
                return B.block_schema(b)
        return None

    def columns(self) -> List[str]:
        for b in self._blocks():
            return B.block_columns(b)
        return []

    def stats(self) -> str:
        return (
            f"Dataset(num_blocks={self.num_blocks()}, num_rows={self.count()}, "
            f"columns={self.columns()})"
        )

    def __repr__(self) -> str:
        return self.stats()

    # -- materialization ---------------------------------------------------
    def to_pandas(self, limit: Optional[int] = None) -> pd.DataFrame:
        dfs = []
        seen = 0
        for b in self._blocks():
            df = B.block_to_pandas(b)
            dfs.append(df)
            seen += len(df)
            if limit is not None and seen >= limit:
                break
        if not dfs:
            return pd.DataFrame()
        out = pd.concat(dfs, ignore_index=True)
        return out.iloc[:limit] if limit is not None else out

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for b in self._blocks():
            df = B.block_to_pandas(b)
            for _, row in df.iterrows():
                rows.append(row.to_dict())
                if len(rows) >= n:
                    return rows
        return rows

    def take_all(self) -> List[Dict[str, Any]]:
        return self.take(self.count())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self._blocks():
            df = B.block_to_pandas(b)
            for _, row in df.iterrows():
                yield row.to_dict()

    def iter_batches(
        self,
        batch_size: Optional[int] = 256,
        batch_format: str = "pandas",
        drop_last: bool = False,
    ):
        """Sequential batch iterator (feeds host→device transfer in the
        trainer; batches are exact-size across block boundaries)."""
        carry: Optional[B.Block] = None
        for b in self._blocks():
            cur = b if carry is None else B.concat_blocks([carry, b])
            carry = None
            n = B.block_num_rows(cur)
            if batch_size is None:
                yield B.to_batch_format(cur, batch_format)
                continue
            start = 0
            while n - start >= batch_size:
                yield B.to_batch_format(
                    B.block_slice(cur, start, start + batch_size), batch_format
                )
                start += batch_size
            if start < n:
                carry = B.block_slice(cur, start, n)
        if carry is not None and not drop_last:
            yield B.to_batch_format(carry, batch_format)

    # -- transforms --------------------------------------------------------
    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = 4096,
        batch_format: str = "pandas",
        compute: Optional[Union[str, ActorPoolStrategy]] = None,
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict[str, Any]] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict[str, Any]] = None,
        num_chips: float = 0,
        **ray_remote_args,
    ) -> "Dataset":
        """Apply ``fn`` to batches of each block, in parallel.

        * default compute: one task per block;
        * ``compute=ActorPoolStrategy(size=k)`` (or a callable class ``fn``):
          a pool of k actors, each constructing ``fn`` once — the predictor
          path (§3.3).
        """
        fn_kwargs = fn_kwargs or {}
        fn_constructor_kwargs = fn_constructor_kwargs or {}
        use_actors = isinstance(compute, ActorPoolStrategy) or isinstance(fn, type)
        if not use_actors:
            task = _map_block
            if num_chips or ray_remote_args:
                task = task.options(num_chips=num_chips or None, **ray_remote_args)
            refs = [
                task.remote(fn, ref, batch_size, batch_format, fn_args, fn_kwargs)
                for ref in self._block_refs
            ]
            return Dataset(refs)

        strategy = compute if isinstance(compute, ActorPoolStrategy) else ActorPoolStrategy()
        pool_size = strategy.size or min(max(strategy.min_size, 1),
                                         max(len(self._block_refs), 1), strategy.max_size)
        chips = num_chips or strategy.num_chips
        worker_cls = _MapWorker.options(num_chips=chips or None, **ray_remote_args)
        actors = [
            worker_cls.remote(fn, fn_constructor_args, fn_constructor_kwargs)
            for _ in range(pool_size)
        ]
        pool = ActorPool(actors)
        out_refs: List[ObjectRef] = []
        pending: List[ObjectRef] = list(self._block_refs)
        try:
            # ordered map over blocks, recycling idle actors
            idx = 0
            while idx < len(pending) and pool.has_free():
                pool.submit(
                    lambda a, v: a.apply.remote(v, batch_size, batch_format, fn_args, fn_kwargs),
                    pending[idx],
                )
                idx += 1
            for _ in range(len(pending)):
                out_refs.append(put(pool.get_next()))
                if idx < len(pending):
                    pool.submit(
                        lambda a, v: a.apply.remote(v, batch_size, batch_format, fn_args, fn_kwargs),
                        pending[idx],
                    )
                    idx += 1
        finally:
            from tpu_air.core import kill

            for a in actors:
                kill(a)
        return Dataset(out_refs)

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            return pd.DataFrame([fn(r.to_dict()) for _, r in df.iterrows()])

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            mask = [bool(fn(r.to_dict())) for _, r in df.iterrows()]
            return df[np.asarray(mask, dtype=bool)]

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda df: df.drop(columns=cols), batch_size=None, batch_format="pandas"
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda df: df[list(cols)], batch_size=None, batch_format="pandas"
        )

    def add_column(self, name: str, fn: Callable[[pd.DataFrame], Any]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            df[name] = fn(df)
            return df

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    # -- shape ops ----------------------------------------------------------
    def limit(self, n: int) -> "Dataset":
        """First n rows (SMALL_DATA dial, Model_finetuning…ipynb:cc-21)."""
        refs: List[ObjectRef] = []
        remaining = n
        for ref in self._block_refs:
            if remaining <= 0:
                break
            blk = get(ref)
            rows = B.block_num_rows(blk)
            if rows <= remaining:
                refs.append(ref)
                remaining -= rows
            else:
                refs.append(put(B.block_slice(blk, 0, remaining)))
                remaining = 0
        return Dataset(refs)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into exactly ``num_blocks`` blocks
        (Introduction…ipynb:cc-11)."""
        df = self.to_pandas()
        n = len(df)
        if n == 0:
            return Dataset([put(B.block_from_pandas(df)) for _ in range(1)])
        sizes = [(n + i) // num_blocks for i in range(num_blocks)]
        refs, start = [], 0
        for s in sizes:
            refs.append(put(B.block_from_pandas(df.iloc[start : start + s])))
            start += s
        return Dataset(refs)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        from .io import df_chunks

        df = self.to_pandas()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(df))
        df = df.iloc[perm].reset_index(drop=True)
        nb = max(1, self.num_blocks())
        return Dataset([put(B.block_from_pandas(part)) for part in df_chunks(df, nb)])

    def train_test_split(
        self, test_size: Union[float, int], *, shuffle: bool = False,
        seed: Optional[int] = None,
    ) -> Tuple["Dataset", "Dataset"]:
        """80/20-style split (Introduction…ipynb:cc-10; the HF-side
        ``train_test_split(seed=57)`` at Model_finetuning…ipynb:cc-13)."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        n = ds.count()
        ntest = int(n * test_size) if isinstance(test_size, float) else int(test_size)
        ntrain = n - ntest
        df = ds.to_pandas()
        train = Dataset([put(B.block_from_pandas(df.iloc[:ntrain]))])
        test = Dataset([put(B.block_from_pandas(df.iloc[ntrain:]))])
        return train, test

    def split(self, n: int, *, equal: bool = True, locality_hints=None) -> List["Dataset"]:
        """Split into n shards — one per DP worker (SURVEY.md §1-L3:
        "partitioned Dataset shards" per worker)."""
        from .io import df_chunks

        df = self.to_pandas()
        total = len(df)
        if equal:
            per = total // n
            parts = [df.iloc[i * per : (i + 1) * per] for i in range(n)]
        else:
            parts = df_chunks(df, n)
        return [Dataset([put(B.block_from_pandas(p))]) for p in parts]

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._block_refs)
        for o in others:
            refs.extend(o._block_refs)
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        left, right = self.to_pandas(), other.to_pandas()
        right = right.rename(
            columns={c: f"{c}_1" for c in right.columns if c in left.columns}
        )
        return Dataset([put(B.block_from_pandas(pd.concat([left, right], axis=1)))])

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        df = self.to_pandas().sort_values(key, ascending=not descending)
        return Dataset([put(B.block_from_pandas(df.reset_index(drop=True)))])

    def groupby(self, key: str) -> "GroupedData":
        """(Introduction…ipynb:cc-18: ``groupby("…").mean("…")``)."""
        return GroupedData(self, key)

    # -- writes -------------------------------------------------------------
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self._blocks()):
            table = (
                blk
                if isinstance(blk, pa.Table)
                else pa.Table.from_pandas(B.block_to_pandas(blk), preserve_index=False)
            )
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self._blocks()):
            B.block_to_pandas(blk).to_csv(
                os.path.join(path, f"part-{i:05d}.csv"), index=False
            )

    def materialize(self) -> "Dataset":
        return self


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, how: str, on: Optional[str]) -> Dataset:
        df = self._ds.to_pandas()
        g = df.groupby(self._key)
        target = g[on] if on else g
        out = getattr(target, how)()
        if isinstance(out, pd.Series):
            out = out.to_frame(name=f"{how}({on})" if on else how)
        else:
            out = out.rename(columns={c: f"{how}({c})" for c in out.columns})
        out = out.reset_index()
        return Dataset([put(B.block_from_pandas(out))])

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self._agg("mean", on)

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self._agg("sum", on)

    def min(self, on: Optional[str] = None) -> Dataset:
        return self._agg("min", on)

    def max(self, on: Optional[str] = None) -> Dataset:
        return self._agg("max", on)

    def std(self, on: Optional[str] = None) -> Dataset:
        return self._agg("std", on)

    def count(self) -> Dataset:
        df = self._ds.to_pandas()
        out = df.groupby(self._key).size().to_frame("count()").reset_index()
        return Dataset([put(B.block_from_pandas(out))])
