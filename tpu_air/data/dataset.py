"""Distributed Dataset: lists of blocks in the object store.

Parity surface (SURVEY.md §1-L2, exercised at the cited cells):
``map_batches`` (Scaling_model_training.ipynb:cc-33), ``limit``
(Model_finetuning…ipynb:cc-21), ``train_test_split`` (Introduction…ipynb:cc-10),
``repartition`` (cc-11), ``schema/count/show/take/to_pandas`` (cc-15-17),
``groupby(...).mean(...)`` (cc-18), ``drop_columns`` (cc-58), plus ``split``
(per-worker shards feeding the Trainer, Model_finetuning…ipynb:cc-29 figure).

Blocks live in the shared-memory object store (core layer) and are processed
in parallel by tasks or an actor pool — preprocessing stays on host CPUs;
device work enters only at the trainer/predictor boundary (SURVEY.md §7
architecture stance).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from tpu_air.core import ObjectRef, get, put, remote
from tpu_air.core.actor_pool import ActorPool

from . import block as B


class ActorPoolStrategy:
    """compute= strategy for map_batches: a fixed/autoscaling pool of actors
    (the architecture behind BatchPredictor, Scaling_batch_inference.ipynb:cc-4
    "autoscaling the actor pool")."""

    def __init__(self, size: Optional[int] = None, min_size: int = 1,
                 max_size: Optional[int] = None, num_chips: float = 0):
        self.size = size
        self.min_size = size or min_size
        self.max_size = size or max_size or max(2, self.min_size)
        self.num_chips = num_chips
        self.scaled_to: Optional[int] = None  # set by map_batches after a run


def _apply_fn_to_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs):
    n = B.block_num_rows(blk)
    if n == 0:
        return blk
    step = batch_size or n
    outs = []
    for start in range(0, n, step):
        batch = B.to_batch_format(B.block_slice(blk, start, min(start + step, n)), batch_format)
        out = fn(batch, *fn_args, **fn_kwargs)
        outs.append(B.from_batch(out))
    return B.concat_blocks(outs)


@remote
def _map_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs):
    return _apply_fn_to_block(fn, blk, batch_size, batch_format, fn_args, fn_kwargs)


# -- block-wise shape-op tasks (no driver materialization) -------------------
# The reference's data plane does "batching, pipelining … and memory
# management" off-driver (Scaling_batch_inference.ipynb:cc-4); these tasks
# keep every all-rows operation in workers reading blocks zero-copy from the
# shared-memory store, so the driver never holds the dataset.


@remote
def _num_rows_task(blk) -> int:
    return B.block_num_rows(blk)


@remote
def _gather_slices(spans, *blks):
    """Concat [blks[i][start:stop] for (i, start, stop) in spans] → one block."""
    parts = [B.block_slice(blks[i], start, stop) for i, start, stop in spans]
    return B.concat_blocks(parts) if parts else B.block_from_rows([])


@remote
def _shuffle_map(blk, nb: int, seed) -> list:
    """Scatter rows of one block uniformly into nb buckets (phase 1 of the
    distributed two-phase shuffle)."""
    n = B.block_num_rows(blk)
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, nb, size=n)
    df = B.block_to_pandas(blk)
    return [B.block_from_pandas(df.iloc[assignment == j]) for j in range(nb)]


@remote
def _shuffle_reduce(j: int, seed, *bucket_lists):
    """Concat bucket j from every map output and locally permute (phase 2)."""
    parts = [bl[j] for bl in bucket_lists]
    blk = B.concat_blocks(parts)
    df = B.block_to_pandas(blk)
    rng = np.random.default_rng(None if seed is None else seed + 40_013 * (j + 1))
    return B.block_from_pandas(
        df.iloc[rng.permutation(len(df))].reset_index(drop=True)
    )


@remote
def _sample_keys(blk, key: str, k: int):
    df = B.block_to_pandas(blk)
    vals = df[key].to_numpy()
    if len(vals) <= k:
        return vals
    idx = np.random.default_rng(0).choice(len(vals), size=k, replace=False)
    return vals[idx]


@remote
def _range_partition(blk, key: str, cuts) -> list:
    """Split one block into len(cuts)+1 key ranges (phase 1 of sample sort).
    Works for any orderable dtype — strings fall back to bisect."""
    import bisect

    df = B.block_to_pandas(blk)
    vals = df[key].to_numpy()
    try:
        bucket = np.searchsorted(np.asarray(cuts), vals, side="right")
    except (TypeError, ValueError):
        bucket = np.fromiter(
            (bisect.bisect_right(cuts, v) for v in vals), dtype=np.int64, count=len(vals)
        )
    return [B.block_from_pandas(df.iloc[bucket == j]) for j in range(len(cuts) + 1)]


@remote
def _range_merge(j: int, key: str, descending: bool, *part_lists):
    parts = [pl[j] for pl in part_lists]
    df = B.block_to_pandas(B.concat_blocks(parts))
    df = df.sort_values(key, ascending=not descending, kind="mergesort")
    return B.block_from_pandas(df.reset_index(drop=True))


@remote
def _zip_blocks(left, right):
    l, r = B.block_to_pandas(left), B.block_to_pandas(right).reset_index(drop=True)
    r = r.rename(columns={c: f"{c}_1" for c in r.columns if c in l.columns})
    return B.block_from_pandas(pd.concat([l.reset_index(drop=True), r], axis=1))


_GROUP_AGGS = ("count", "sum", "min", "max", "sumsq")


@remote
def _group_partial(blk, key: str):
    """Per-block partial aggregates; partials are tiny (one row per group) so
    the driver-side merge never sees the data itself.  sum/sumsq cover
    numeric columns; min/max cover any orderable dtype (string min/max is
    valid pandas groupby behavior)."""
    df = B.block_to_pandas(blk)
    g = df.groupby(key, dropna=False)
    out = pd.DataFrame({"__count": g.size()})
    for c in df.columns:
        if c == key:
            continue
        if pd.api.types.is_numeric_dtype(df[c]):
            out[f"__sum_{c}"] = g[c].sum()
            out[f"__sumsq_{c}"] = g[c].apply(
                lambda s: float((s.astype(float) ** 2).sum())
            )
        try:
            out[f"__min_{c}"] = g[c].min()
            out[f"__max_{c}"] = g[c].max()
        except (TypeError, ValueError):
            pass  # unorderable dtype (e.g. dicts) — no min/max partial
    return out.reset_index()


@remote
class _MapWorker:
    """Actor for callable-class map_batches (holds expensive state, e.g. a
    model on a leased chip)."""

    def __init__(self, fn_or_cls, constructor_args, constructor_kwargs):
        if isinstance(fn_or_cls, type):
            self.fn = fn_or_cls(*constructor_args, **constructor_kwargs)
        else:
            self.fn = fn_or_cls

    def apply(self, blk, batch_size, batch_format, fn_args, fn_kwargs):
        return _apply_fn_to_block(self.fn, blk, batch_size, batch_format, fn_args, fn_kwargs)


class Dataset:
    """A distributed dataset = ordered list of block refs."""

    def __init__(self, block_refs: List[ObjectRef]):
        self._block_refs = list(block_refs)
        self._cached_num_rows: Optional[int] = None

    # -- introspection -----------------------------------------------------
    def num_blocks(self) -> int:
        return len(self._block_refs)

    def get_internal_block_refs(self) -> List[ObjectRef]:
        return list(self._block_refs)

    def _blocks(self) -> Iterator[B.Block]:
        for ref in self._block_refs:
            yield get(ref)

    def count(self) -> int:
        if self._cached_num_rows is None:
            self._row_counts()  # worker-side counting; caches the total
        return self._cached_num_rows

    def __len__(self) -> int:  # convenience; Ray deprecates this but HF uses len()
        return self.count()

    def schema(self):
        for b in self._blocks():
            if B.block_num_rows(b) > 0:
                return B.block_schema(b)
        return None

    def columns(self) -> List[str]:
        for b in self._blocks():
            return B.block_columns(b)
        return []

    def stats(self) -> str:
        return (
            f"Dataset(num_blocks={self.num_blocks()}, num_rows={self.count()}, "
            f"columns={self.columns()})"
        )

    def __repr__(self) -> str:
        return self.stats()

    # -- materialization ---------------------------------------------------
    def to_pandas(self, limit: Optional[int] = None) -> pd.DataFrame:
        dfs = []
        seen = 0
        for b in self._blocks():
            df = B.block_to_pandas(b)
            dfs.append(df)
            seen += len(df)
            if limit is not None and seen >= limit:
                break
        if not dfs:
            return pd.DataFrame()
        out = pd.concat(dfs, ignore_index=True)
        return out.iloc[:limit] if limit is not None else out

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for b in self._blocks():
            df = B.block_to_pandas(b)
            for _, row in df.iterrows():
                rows.append(row.to_dict())
                if len(rows) >= n:
                    return rows
        return rows

    def take_all(self) -> List[Dict[str, Any]]:
        return self.take(self.count())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self._blocks():
            df = B.block_to_pandas(b)
            for _, row in df.iterrows():
                yield row.to_dict()

    def iter_batches(
        self,
        batch_size: Optional[int] = 256,
        batch_format: str = "pandas",
        drop_last: bool = False,
    ):
        """Sequential batch iterator (feeds host→device transfer in the
        trainer; batches are exact-size across block boundaries)."""
        carry: Optional[B.Block] = None
        for b in self._blocks():
            cur = b if carry is None else B.concat_blocks([carry, b])
            carry = None
            n = B.block_num_rows(cur)
            if batch_size is None:
                yield B.to_batch_format(cur, batch_format)
                continue
            start = 0
            while n - start >= batch_size:
                yield B.to_batch_format(
                    B.block_slice(cur, start, start + batch_size), batch_format
                )
                start += batch_size
            if start < n:
                carry = B.block_slice(cur, start, n)
        if carry is not None and not drop_last:
            yield B.to_batch_format(carry, batch_format)

    # -- transforms --------------------------------------------------------
    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = 4096,
        batch_format: str = "pandas",
        compute: Optional[Union[str, ActorPoolStrategy]] = None,
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict[str, Any]] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict[str, Any]] = None,
        num_chips: float = 0,
        **ray_remote_args,
    ) -> "Dataset":
        """Apply ``fn`` to batches of each block, in parallel.

        * default compute: one task per block;
        * ``compute=ActorPoolStrategy(size=k)`` (or a callable class ``fn``):
          a pool of k actors, each constructing ``fn`` once — the predictor
          path (§3.3).
        """
        fn_kwargs = fn_kwargs or {}
        fn_constructor_kwargs = fn_constructor_kwargs or {}
        use_actors = isinstance(compute, ActorPoolStrategy) or isinstance(fn, type)
        if not use_actors:
            task = _map_block
            if num_chips or ray_remote_args:
                task = task.options(num_chips=num_chips or None, **ray_remote_args)
            refs = [
                task.remote(fn, ref, batch_size, batch_format, fn_args, fn_kwargs)
                for ref in self._block_refs
            ]
            return Dataset(refs)

        strategy = compute if isinstance(compute, ActorPoolStrategy) else ActorPoolStrategy()
        min_size = strategy.size or max(strategy.min_size, 1)
        max_size = strategy.size or max(strategy.max_size, min_size)
        min_size = min(min_size, max(len(self._block_refs), 1))
        chips = num_chips or strategy.num_chips
        worker_cls = _MapWorker.options(num_chips=chips or None, **ray_remote_args)

        def make_actor():
            return worker_cls.remote(fn, fn_constructor_args, fn_constructor_kwargs)

        submit = lambda a, v: a.apply.remote(  # noqa: E731
            v, batch_size, batch_format, fn_args, fn_kwargs
        )
        actors = [make_actor() for _ in range(min_size)]
        pool = ActorPool(actors)
        out_refs: List[ObjectRef] = []
        pending: List[ObjectRef] = list(self._block_refs)
        try:
            idx = 0
            while idx < len(pending) and pool.has_free():
                pool.submit(submit, pending[idx])
                idx += 1
            scale_blocked = False

            def can_scale() -> bool:
                if scale_blocked or pool.size() >= max_size:
                    return False
                # grow only while there's enough queued work to keep the
                # bigger pool busy (>= 2 blocks per actor) — spinning up an
                # actor per near-empty block costs more than it saves
                if len(pending) - idx < 2 * (pool.size() + 1):
                    return False
                if not chips:
                    return True
                # A chip-leased scale-up actor queues for a lease the pool's
                # own actors may hold until THIS map_batches ends.  The free-
                # lease check below is advisory (a concurrent consumer can
                # take the chip between check and placement), so placement is
                # CONFIRMED via the ready() probe before any block is
                # submitted to the new actor.
                from tpu_air.core.runtime import get_runtime

                return get_runtime().avail.get("chip", 0.0) >= float(chips)

            def try_scale_up():
                """Create an actor and submit to it only once its placement
                is confirmed; an actor stuck queued behind the pool's own
                leases is killed and scaling stops (fall back to the
                existing pool) — never feed the ordered, timeout-less
                get_next() an actor that may never be placed."""
                nonlocal scale_blocked
                import time as _time

                from tpu_air.core import get, kill
                from tpu_air.core.runtime import get_runtime

                a = make_actor()
                # Phase 1: bounded wait for the LEASE.  The free-lease check
                # in can_scale is advisory (TOCTOU) — a concurrent consumer
                # may have taken the chip, leaving this creation queued
                # behind leases our own pool holds until map_batches ends.
                rt = get_runtime()
                deadline = _time.monotonic() + 5.0
                while rt.actor_pending_placement(a._actor_id):
                    if _time.monotonic() > deadline:
                        kill(a)
                        scale_blocked = True
                        return False
                    _time.sleep(0.02)
                # Phase 2: lease claimed — construction may legitimately be
                # slow (heavy model load is what _MapWorker exists for), so
                # no timeout here.  A crashed constructor resolves the ready
                # ref with an error sentinel, so this cannot hang.
                try:
                    if a._ready_ref is not None:
                        get(a._ready_ref)
                except Exception:  # noqa: BLE001 — ctor failure arrives as an arbitrary unpickled error
                    kill(a)
                    scale_blocked = True
                    return False
                actors.append(a)
                pool.push(a)
                return True

            for _ in range(len(pending)):
                # Autoscale under backlog: all actors busy and blocks still
                # queued → grow toward max_size before blocking on a result
                # (Scaling_batch_inference.ipynb:cc-4 "autoscaling the actor
                # pool").
                while idx < len(pending) and not pool.has_free() and can_scale():
                    if not try_scale_up():
                        break
                    pool.submit(submit, pending[idx])
                    idx += 1
                out_refs.append(put(pool.get_next()))
                if idx < len(pending):
                    pool.submit(submit, pending[idx])
                    idx += 1
            strategy.scaled_to = pool.size()  # observable for tests/stats
        finally:
            from tpu_air.core import kill

            for a in actors:
                kill(a)
        return Dataset(out_refs)

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            return pd.DataFrame([fn(r.to_dict()) for _, r in df.iterrows()])

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            mask = [bool(fn(r.to_dict())) for _, r in df.iterrows()]
            return df[np.asarray(mask, dtype=bool)]

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda df: df.drop(columns=cols), batch_size=None, batch_format="pandas"
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda df: df[list(cols)], batch_size=None, batch_format="pandas"
        )

    def add_column(self, name: str, fn: Callable[[pd.DataFrame], Any]) -> "Dataset":
        def batch_fn(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            df[name] = fn(df)
            return df

        return self.map_batches(batch_fn, batch_size=None, batch_format="pandas")

    # -- shape ops (block-wise via tasks; the driver only ever sees row
    # counts and tiny metadata, never the rows themselves) -------------------
    def _row_counts(self) -> List[int]:
        refs = [_num_rows_task.remote(r) for r in self._block_refs]
        counts = get(refs)
        self._cached_num_rows = int(sum(counts))
        return counts

    def _row_range_refs(
        self, start: int, stop: int, counts: List[int]
    ) -> List[ObjectRef]:
        """Refs covering global rows [start, stop).  Whole blocks pass
        through by reference (zero copy); partial blocks become slice tasks."""
        refs: List[ObjectRef] = []
        off = 0
        for ref, n in zip(self._block_refs, counts):
            lo, hi = max(start - off, 0), min(stop - off, n)
            if lo < hi:
                if lo == 0 and hi == n:
                    refs.append(ref)
                else:
                    refs.append(_gather_slices.remote([(0, lo, hi)], ref))
            off += n
            if off >= stop:
                break
        return refs

    def limit(self, n: int) -> "Dataset":
        """First n rows (SMALL_DATA dial, Model_finetuning…ipynb:cc-21)."""
        return Dataset(self._row_range_refs(0, n, self._row_counts()))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into exactly ``num_blocks`` blocks
        (Introduction…ipynb:cc-11).  Each output block is assembled by one
        task from the input slices that overlap its row range."""
        counts = self._row_counts()
        total = sum(counts)
        offsets = np.cumsum([0] + counts)
        sizes = [(total + i) // num_blocks for i in range(num_blocks)]
        refs, start = [], 0
        for s in sizes:
            stop = start + s
            spans, blks = [], []
            for bi, n in enumerate(counts):
                lo = max(start - offsets[bi], 0)
                hi = min(stop - offsets[bi], n)
                if lo < hi:
                    spans.append((len(blks), int(lo), int(hi)))
                    blks.append(self._block_refs[bi])
            refs.append(_gather_slices.remote(spans, *blks))
            start = stop
        return Dataset(refs)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Distributed two-phase shuffle: per-block uniform scatter into
        num_blocks buckets, then per-bucket concat + local permutation —
        rows never pass through the driver."""
        nb = max(1, self.num_blocks())
        map_refs = [
            _shuffle_map.remote(ref, nb, None if seed is None else seed + i)
            for i, ref in enumerate(self._block_refs)
        ]
        return Dataset(
            [_shuffle_reduce.remote(j, seed, *map_refs) for j in range(nb)]
        )

    def train_test_split(
        self, test_size: Union[float, int], *, shuffle: bool = False,
        seed: Optional[int] = None,
    ) -> Tuple["Dataset", "Dataset"]:
        """80/20-style split (Introduction…ipynb:cc-10; the HF-side
        ``train_test_split(seed=57)`` at Model_finetuning…ipynb:cc-13)."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        counts = ds._row_counts()
        n = sum(counts)
        ntest = int(n * test_size) if isinstance(test_size, float) else int(test_size)
        ntrain = n - ntest
        train = Dataset(ds._row_range_refs(0, ntrain, counts))
        test = Dataset(ds._row_range_refs(ntrain, n, counts))
        return train, test

    def split(self, n: int, *, equal: bool = True, locality_hints=None) -> List["Dataset"]:
        """Split into n shards — one per DP worker (SURVEY.md §1-L3:
        "partitioned Dataset shards" per worker)."""
        counts = self._row_counts()
        total = sum(counts)
        if equal:
            per = total // n
            bounds = [(i * per, (i + 1) * per) for i in range(n)]
        else:
            sizes = [(total + i) // n for i in range(n)]
            offs = np.cumsum([0] + sizes)
            bounds = [(int(offs[i]), int(offs[i + 1])) for i in range(n)]
        return [Dataset(self._row_range_refs(lo, hi, counts)) for lo, hi in bounds]

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._block_refs)
        for o in others:
            refs.extend(o._block_refs)
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip: the right side is realigned to the left's block
        boundaries, then blocks are zipped pairwise by tasks."""
        counts = self._row_counts()
        offsets = np.cumsum([0] + counts)
        rcounts = other._row_counts()
        refs = []
        for bi, n in enumerate(counts):
            lo, hi = int(offsets[bi]), int(offsets[bi] + n)
            right_refs = other._row_range_refs(lo, hi, rcounts)
            if len(right_refs) == 1:
                right = right_refs[0]
            else:
                rns = get([_num_rows_task.remote(r) for r in right_refs])
                right = _gather_slices.remote(
                    [(i, 0, int(rn)) for i, rn in enumerate(rns)], *right_refs
                )
            refs.append(_zip_blocks.remote(self._block_refs[bi], right))
        return Dataset(refs)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample sort: sample cut points, range-partition each
        block, merge+sort each range in its own task."""
        nb = max(1, self.num_blocks())
        if nb == 1:
            return Dataset(
                [_range_merge.remote(0, key, descending, _range_partition.remote(self._block_refs[0], key, []))]
            )
        samples = sorted(
            v
            for s in get([_sample_keys.remote(r, key, 64) for r in self._block_refs])
            for v in np.asarray(s).tolist()
        )
        if not samples:  # all blocks empty — nothing to order
            return Dataset(list(self._block_refs))
        # positional quantiles: dtype-agnostic (numeric or string keys)
        picks = [samples[(len(samples) * (i + 1)) // nb] for i in range(nb - 1)]
        cuts = sorted(set(picks))
        part_refs = [_range_partition.remote(r, key, cuts) for r in self._block_refs]
        refs = [
            _range_merge.remote(j, key, descending, *part_refs)
            for j in range(len(cuts) + 1)
        ]
        if descending:
            refs = refs[::-1]
        return Dataset(refs)

    def groupby(self, key: str) -> "GroupedData":
        """(Introduction…ipynb:cc-18: ``groupby("…").mean("…")``)."""
        return GroupedData(self, key)

    # -- writes -------------------------------------------------------------
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self._blocks()):
            table = (
                blk
                if isinstance(blk, pa.Table)
                else pa.Table.from_pandas(B.block_to_pandas(blk), preserve_index=False)
            )
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self._blocks()):
            B.block_to_pandas(blk).to_csv(
                os.path.join(path, f"part-{i:05d}.csv"), index=False
            )

    def materialize(self) -> "Dataset":
        return self


class GroupedData:
    """Distributed groupby: each block computes one-row-per-group partial
    aggregates (count/sum/min/max/sumsq) in a task; the driver only merges
    those tiny partials and finalizes the requested statistic."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _merged_partials(self) -> pd.DataFrame:
        parts = get([_group_partial.remote(r, self._key) for r in self._ds._block_refs])
        parts = [p for p in parts if len(p)]
        if not parts:
            return pd.DataFrame({self._key: [], "__count": []})
        allp = pd.concat(parts, ignore_index=True)
        g = allp.groupby(self._key, dropna=False)
        merged = pd.DataFrame({"__count": g["__count"].sum()})
        for c in allp.columns:
            if c.startswith("__sum_") or c.startswith("__sumsq_"):
                merged[c] = g[c].sum()
            elif c.startswith("__min_"):
                merged[c] = g[c].min()
            elif c.startswith("__max_"):
                merged[c] = g[c].max()
        return merged.reset_index()

    def _finalize(self, how: str, on: Optional[str]) -> Dataset:
        m = self._merged_partials()
        prefix = "__min_" if how in ("min", "max") else "__sum_"
        cols = sorted(
            {c[len(prefix):] for c in m.columns if c.startswith(prefix)}
        )
        targets = [on] if on else cols
        out = pd.DataFrame({self._key: m[self._key]})
        for c in targets:
            if f"{prefix}{c}" not in m.columns:
                raise ValueError(
                    f"groupby.{how}() unsupported for column {c!r} "
                    f"({'non-orderable' if how in ('min', 'max') else 'non-numeric'})"
                )
            if how == "mean":
                out[f"mean({c})"] = m[f"__sum_{c}"] / m["__count"]
            elif how == "sum":
                out[f"sum({c})"] = m[f"__sum_{c}"]
            elif how == "min":
                out[f"min({c})"] = m[f"__min_{c}"]
            elif how == "max":
                out[f"max({c})"] = m[f"__max_{c}"]
            elif how == "std":
                n, s, ss = m["__count"], m[f"__sum_{c}"], m[f"__sumsq_{c}"]
                var = (ss - s * s / n) / (n - 1).clip(lower=1)
                out[f"std({c})"] = np.sqrt(var.clip(lower=0.0))
        out = out.sort_values(self._key).reset_index(drop=True)
        return Dataset([put(B.block_from_pandas(out))])

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self._finalize("mean", on)

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self._finalize("sum", on)

    def min(self, on: Optional[str] = None) -> Dataset:
        return self._finalize("min", on)

    def max(self, on: Optional[str] = None) -> Dataset:
        return self._finalize("max", on)

    def std(self, on: Optional[str] = None) -> Dataset:
        return self._finalize("std", on)

    def count(self) -> Dataset:
        m = self._merged_partials()
        out = pd.DataFrame({self._key: m[self._key], "count()": m["__count"]})
        out = out.sort_values(self._key).reset_index(drop=True)
        return Dataset([put(B.block_from_pandas(out))])
