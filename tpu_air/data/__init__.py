"""tpu_air.data — distributed datasets over shared-memory blocks (L2)."""

from . import preprocessors
from .dataset import ActorPoolStrategy, Dataset, GroupedData
from .io import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_parquet,
)
from .preprocessors import (
    BatchMapper,
    Chain,
    MinMaxScaler,
    Normalizer,
    PowerTransformer,
    Preprocessor,
    StandardScaler,
)

__all__ = [
    "ActorPoolStrategy",
    "BatchMapper",
    "Chain",
    "Dataset",
    "GroupedData",
    "MinMaxScaler",
    "Normalizer",
    "PowerTransformer",
    "Preprocessor",
    "StandardScaler",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "preprocessors",
    "range",
    "read_csv",
    "read_json",
    "read_parquet",
]
