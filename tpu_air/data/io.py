"""Dataset constructors / readers.

Parity surface (SURVEY.md §1-L2): ``from_huggingface``
(Model_finetuning…ipynb:cc-18), ``from_items`` (Scaling_batch_inference.ipynb:cc-70),
``read_parquet`` (Introduction…ipynb:cc-9), plus ``from_pandas``/``from_numpy``/
``from_arrow``/``read_csv``/``range``.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List, Optional, Union

import numpy as np
import pandas as pd

from tpu_air.core import put

from . import block as B
from .dataset import Dataset

_DEFAULT_PARALLELISM = 8


def df_chunks(df: pd.DataFrame, nb: int):
    """Split a DataFrame into nb nearly-equal row slices."""
    n = len(df)
    nb = max(1, nb)
    # NB: this module defines a Dataset-producing ``range`` — use the builtin.
    bounds = [round(i * n / nb) for i in builtins.range(nb + 1)]
    return [
        df.iloc[bounds[i] : bounds[i + 1]].reset_index(drop=True)
        for i in builtins.range(nb)
    ]


def _split_df(df: pd.DataFrame, parallelism: int) -> Dataset:
    nb = max(1, min(parallelism, len(df)) or 1)
    parts = df_chunks(df, nb) if len(df) else [df]
    return Dataset([put(B.block_from_pandas(p)) for p in parts])


def from_items(items: List[Any], parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """List of dicts → columns; list of arbitrary objects → column "item"
    (ray.data.from_items parity)."""
    if items and isinstance(items[0], dict):
        df = pd.DataFrame(items)
    else:
        df = pd.DataFrame({B.VALUE_COLUMN: list(items)})
    return _split_df(df, parallelism)


def from_pandas(dfs: Union[pd.DataFrame, List[pd.DataFrame]]) -> Dataset:
    if isinstance(dfs, pd.DataFrame):
        return Dataset([put(B.block_from_pandas(dfs))])
    return Dataset([put(B.block_from_pandas(df)) for df in dfs])


def from_numpy(arrs: Union[np.ndarray, List[np.ndarray]], column: str = "data") -> Dataset:
    if isinstance(arrs, np.ndarray):
        arrs = [arrs]
    return Dataset(
        [put(B.block_from_pandas(pd.DataFrame({column: list(a)}))) for a in arrs]
    )


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return Dataset([put(t) for t in tables])


def from_huggingface(dataset):
    """Convert a HuggingFace ``datasets.Dataset`` (or DatasetDict) into
    tpu_air Dataset(s) (Model_finetuning…ipynb:cc-18 converts the Alpaca
    DatasetDict)."""
    try:
        import datasets as hf_datasets
    except ImportError as e:  # pragma: no cover
        raise ImportError("from_huggingface requires the 'datasets' package") from e

    if isinstance(dataset, hf_datasets.DatasetDict):
        return {k: from_huggingface(v) for k, v in dataset.items()}
    df = dataset.to_pandas()
    return _split_df(df, _DEFAULT_PARALLELISM)


def range(n: int, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return _split_df(pd.DataFrame({"id": np.arange(n)}), parallelism)


def _expand_paths(paths: Union[str, List[str]], suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if p.startswith(("s3://", "gs://")):
            out.append(p)  # handed to pyarrow's filesystem layer
        elif os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, f"*{suffix}"))))
        else:
            out.append(p)
    return out


def read_parquet(
    paths: Union[str, List[str]],
    columns: Optional[List[str]] = None,
    parallelism: int = _DEFAULT_PARALLELISM,
) -> Dataset:
    """Parquet reader over local or object-store paths
    (``read_parquet("s3://…")``, Introduction…ipynb:cc-9; remote filesystems
    resolved by pyarrow.fs, subject to network availability)."""
    import pyarrow.parquet as pq

    files = _expand_paths(paths, ".parquet")
    refs = []
    for f in files:
        table = pq.read_table(f, columns=columns)
        refs.append(put(table))
    ds = Dataset(refs)
    if len(files) < parallelism:
        total = ds.count()
        if total >= parallelism:
            ds = ds.repartition(parallelism)
    return ds


def read_csv(paths: Union[str, List[str]], parallelism: int = _DEFAULT_PARALLELISM,
             **pandas_kwargs) -> Dataset:
    files = _expand_paths(paths, ".csv")
    dfs = [pd.read_csv(f, **pandas_kwargs) for f in files]
    if len(dfs) == 1:
        return _split_df(dfs[0], parallelism)
    return from_pandas(dfs)


def read_json(paths: Union[str, List[str]], parallelism: int = _DEFAULT_PARALLELISM,
              **pandas_kwargs) -> Dataset:
    files = _expand_paths(paths, ".json")
    dfs = [pd.read_json(f, **pandas_kwargs) for f in files]
    if len(dfs) == 1:
        return _split_df(dfs[0], parallelism)
    return from_pandas(dfs)
