"""Persistent preprocessors.

Parity surface (SURVEY.md §1-L2): ``BatchMapper`` (Model_finetuning…ipynb:cc-27),
``MinMaxScaler`` (Introduction…ipynb:cc-20-21), ``PowerTransformer``
(Introduction…ipynb:cc-25), ``Normalizer`` (cc-27), plus ``StandardScaler``
and ``Chain``.

The critical contract (Introduction…ipynb:cc-19, predictor.py:93): a
Preprocessor is *fitted during training, saved inside the Checkpoint, and
re-applied automatically to batches at predict time* — so it must be
serializable with its fitted state (plain cloudpickle of ``self``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np
import pandas as pd


class Preprocessor:
    """Base class. Subclasses implement ``_fit(dataset)`` (optional) and
    ``_transform_pandas(df)``."""

    _is_fittable = True

    def __init__(self):
        self._fitted = False

    # -- fitting -----------------------------------------------------------
    def fit(self, dataset) -> "Preprocessor":
        if self._is_fittable:
            self._fit(dataset)
        self._fitted = True
        return self

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def _fit(self, dataset):  # pragma: no cover - default no-op
        pass

    def check_is_fitted(self) -> bool:
        return self._fitted or not self._is_fittable

    # -- transforming ------------------------------------------------------
    def transform(self, dataset):
        """Apply to a Dataset, producing a new Dataset."""
        return dataset.map_batches(self._transform_pandas, batch_format="pandas")

    def transform_batch(self, batch):
        """Apply to a single in-memory batch (predict path — the reference
        applies the checkpointed preprocessor per batch, §3.3)."""
        from .block import block_to_pandas, from_batch, to_batch_format

        if isinstance(batch, pd.DataFrame):
            return self._transform_pandas(batch.copy())
        if isinstance(batch, dict):
            df = block_to_pandas(from_batch(batch))
            out = self._transform_pandas(df)
            return to_batch_format(from_batch(out), "numpy")
        return self._transform_pandas(pd.DataFrame(batch))

    def _transform_pandas(self, df: pd.DataFrame) -> pd.DataFrame:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(fitted={self._fitted})"


class BatchMapper(Preprocessor):
    """Stateless function preprocessor
    (``BatchMapper(preprocess_function, batch_format="pandas", batch_size=4096)``,
    Model_finetuning…ipynb:cc-27)."""

    _is_fittable = False

    def __init__(
        self,
        fn: Callable,
        batch_format: str = "pandas",
        batch_size: Optional[int] = 4096,
    ):
        super().__init__()
        self.fn = fn
        self.batch_format = batch_format
        self.batch_size = batch_size

    def transform(self, dataset):
        return dataset.map_batches(
            self.fn, batch_format=self.batch_format, batch_size=self.batch_size
        )

    def transform_batch(self, batch):
        from .block import block_to_pandas, from_batch, to_batch_format

        if self.batch_format == "pandas" and not isinstance(batch, pd.DataFrame):
            batch = block_to_pandas(from_batch(batch))
        elif self.batch_format == "numpy" and isinstance(batch, pd.DataFrame):
            batch = to_batch_format(from_batch(batch), "numpy")
        return self.fn(batch)

    def _transform_pandas(self, df):
        return self.fn(df)


class MinMaxScaler(Preprocessor):
    """Scale columns to [0, 1] by fitted min/max (Introduction…ipynb:cc-20)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, dataset):
        df = dataset.to_pandas()
        self.stats_ = {
            c: (float(df[c].min()), float(df[c].max())) for c in self.columns
        }

    def _transform_pandas(self, df):
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = hi - lo
            df[c] = 0.0 if span == 0 else (df[c] - lo) / span
        return df


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, dataset):
        df = dataset.to_pandas()
        self.stats_ = {
            c: (float(df[c].mean()), float(df[c].std() or 1.0)) for c in self.columns
        }

    def _transform_pandas(self, df):
        for c in self.columns:
            mu, sd = self.stats_[c]
            df[c] = (df[c] - mu) / (sd if sd else 1.0)
        return df


class PowerTransformer(Preprocessor):
    """Box-Cox / Yeo-Johnson style power transform with explicit power
    (``PowerTransformer(columns, power)``, Introduction…ipynb:cc-25)."""

    _is_fittable = False

    def __init__(self, columns: List[str], power: float, method: str = "yeo-johnson"):
        super().__init__()
        self.columns = columns
        self.power = power
        self.method = method

    def _yeo_johnson(self, x: np.ndarray) -> np.ndarray:
        lam = self.power
        out = np.empty_like(x, dtype=np.float64)
        pos = x >= 0
        if lam != 0:
            out[pos] = ((x[pos] + 1.0) ** lam - 1.0) / lam
        else:
            out[pos] = np.log1p(x[pos])
        if lam != 2:
            out[~pos] = -(((-x[~pos] + 1.0) ** (2.0 - lam)) - 1.0) / (2.0 - lam)
        else:
            out[~pos] = -np.log1p(-x[~pos])
        return out

    def _transform_pandas(self, df):
        for c in self.columns:
            x = df[c].to_numpy(dtype=np.float64)
            if self.method == "yeo-johnson":
                df[c] = self._yeo_johnson(x)
            else:  # box-cox (positive inputs)
                lam = self.power
                df[c] = np.log(x) if lam == 0 else (x**lam - 1.0) / lam
        return df


class Normalizer(Preprocessor):
    """Row-wise vector normalization (named at Introduction…ipynb:cc-27)."""

    _is_fittable = False

    def __init__(self, columns: List[str], norm: str = "l2"):
        super().__init__()
        self.columns = columns
        self.norm = norm

    def _transform_pandas(self, df):
        mat = df[self.columns].to_numpy(dtype=np.float64)
        if self.norm == "l2":
            denom = np.sqrt((mat**2).sum(axis=1))
        elif self.norm == "l1":
            denom = np.abs(mat).sum(axis=1)
        elif self.norm == "max":
            denom = np.abs(mat).max(axis=1)
        else:
            raise ValueError(f"unknown norm {self.norm!r}")
        denom[denom == 0] = 1.0
        df[self.columns] = mat / denom[:, None]
        return df


class Chain(Preprocessor):
    """Sequential composition of preprocessors."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def _fit(self, dataset):
        for p in self.preprocessors:
            dataset = p.fit_transform(dataset)

    def fit_transform(self, dataset):
        for p in self.preprocessors:
            dataset = p.fit_transform(dataset)
        self._fitted = True
        return dataset

    def transform(self, dataset):
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
