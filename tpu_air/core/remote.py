"""``@tpu_air.remote`` — remote functions and actor classes.

API parity targets (SURVEY.md §1-L1): ``@ray.remote`` on functions
(Overview_of_Ray.ipynb:cc-41) and classes (Scaling_batch_inference.ipynb:cc-105),
``.remote(...)`` invocation, ``.options(...)`` resource overrides
(``num_gpus_per_worker`` analog is ``num_chips``), and actor handles whose
methods are invoked as ``handle.method.remote(...)``.

Both the driver and worker processes may call ``.remote`` — nested submission
from a worker is routed to the driver scheduler over the worker's control pipe
(runtime.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from . import runtime as rt
from . import serialization
from .object_store import ObjectRef, new_object_id
from tpu_air.faults import plan as _faults
from tpu_air.observability import tracing as _tracing


def _normalize_resources(
    num_cpus=None, num_chips=None, resources=None, is_actor=False
) -> Dict[str, float]:
    # Like the reference runtime: tasks default to 1 CPU; *actors* default to
    # 0 CPUs for their lifetime (otherwise long-lived actors starve the task
    # pool).  Chip leases are always explicit.
    default_cpu = 0.0 if is_actor else 1.0
    res = dict(resources or {})
    res["cpu"] = float(num_cpus if num_cpus is not None else res.get("cpu", default_cpu))
    if num_chips is not None:
        res["chip"] = float(num_chips)
    else:
        res.setdefault("chip", 0.0)
    return res


def _pack_payload_local(store, payload_tuple):
    blob = serialization.dumps(payload_tuple)
    if len(blob) <= rt._INLINE_LIMIT:
        return blob, None
    return None, store.put(blob).id


def _submit_task(fn, args, kwargs, resources) -> ObjectRef:
    # capture the ambient span context at the submit call-site — None when
    # tracing is off or no span is active, so the common path ships nothing
    trace_ctx = _tracing.current_propagation()
    ctx = rt.current_worker()
    if ctx is not None:
        task_id = new_object_id()
        payload, payload_ref = _pack_payload_local(ctx.store, (fn, list(args), kwargs))
        ctx.send(
            (
                "submit",
                {
                    "task_id": task_id,
                    "payload": payload,
                    "payload_ref": payload_ref,
                    "resources": resources,
                    "trace_ctx": trace_ctx,
                },
            )
        )
        return ObjectRef(task_id)
    return rt.get_runtime().submit_task(fn, list(args), kwargs, resources,
                                        trace_ctx=trace_ctx)


def _create_actor(cls, args, kwargs, resources, name=None) -> "ActorHandle":
    trace_ctx = _tracing.current_propagation()
    ctx = rt.current_worker()
    if ctx is not None:
        actor_id = new_object_id()
        ready_id = new_object_id()
        payload, payload_ref = _pack_payload_local(ctx.store, (cls, list(args), kwargs))
        ctx.send(
            (
                "create_actor",
                {
                    "actor_id": actor_id,
                    "ready_id": ready_id,
                    "payload": payload,
                    "payload_ref": payload_ref,
                    "resources": resources,
                    "name": name,
                    "trace_ctx": trace_ctx,
                },
            )
        )
        return ActorHandle(actor_id, cls.__name__, ObjectRef(ready_id))
    r = rt.get_runtime()
    actor_id, ready_ref = r.create_actor(cls, list(args), kwargs, resources,
                                         name=name, trace_ctx=trace_ctx)
    return ActorHandle(actor_id, cls.__name__, ready_ref)


def _submit_actor_task(actor_id, method, args, kwargs) -> ObjectRef:
    trace_ctx = _tracing.current_propagation()
    ctx = rt.current_worker()
    if _faults.enabled():
        spec = _faults.perturb("actor.call", key=f"{actor_id}:{method}")
        if spec is not None and spec.action == "kill" and ctx is None:
            # crash the TARGET actor's process (no graceful shutdown) so the
            # caller exercises the real pipe-EOF death path
            rt.get_runtime().crash_actor(actor_id)
    if ctx is not None:
        task_id = new_object_id()
        payload, payload_ref = _pack_payload_local(ctx.store, (None, list(args), kwargs))
        ctx.send(
            (
                "actor_call",
                {
                    "task_id": task_id,
                    "payload": payload,
                    "payload_ref": payload_ref,
                    "resources": {},
                    "kind": "actor_task",
                    "actor_id": actor_id,
                    "method": method,
                    "trace_ctx": trace_ctx,
                },
            )
        )
        return ObjectRef(task_id)
    return rt.get_runtime().submit_actor_task(actor_id, method, list(args), kwargs,
                                              trace_ctx=trace_ctx)


class RemoteFunction:
    def __init__(self, fn, resources: Dict[str, float]):
        self._fn = fn
        self._resources = resources
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs) -> ObjectRef:
        return _submit_task(self._fn, args, kwargs, dict(self._resources))

    def options(self, num_cpus=None, num_chips=None, resources=None, **_ignored):
        merged = dict(self._resources)
        if num_cpus is not None:
            merged["cpu"] = float(num_cpus)
        if num_chips is not None:
            merged["chip"] = float(num_chips)
        if resources:
            merged.update(resources)
        return RemoteFunction(self._fn, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._fn, '__name__', self._fn)}' cannot be "
            "called directly; use '.remote()'."
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ObjectRef:
        return _submit_actor_task(self._handle._actor_id, self._name, args, kwargs)


class ActorHandle:
    """Serializable handle to a live actor (``ray.actor.ActorHandle`` analog)."""

    def __init__(self, actor_id: str, class_name: str, ready_ref: Optional[ObjectRef]):
        self._actor_id = actor_id
        self._class_name = class_name
        self._ready_ref = ready_ref

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._ready_ref))


class ActorClass:
    def __init__(self, cls, resources: Dict[str, float], name: Optional[str] = None):
        self._cls = cls
        self._resources = resources
        self._name = name

    def remote(self, *args, **kwargs) -> ActorHandle:
        return _create_actor(self._cls, args, kwargs, dict(self._resources), self._name)

    def options(self, num_cpus=None, num_chips=None, resources=None, name=None, **_ig):
        merged = dict(self._resources)
        if num_cpus is not None:
            merged["cpu"] = float(num_cpus)
        if num_chips is not None:
            merged["chip"] = float(num_chips)
        if resources:
            merged.update(resources)
        return ActorClass(self._cls, merged, name=name or self._name)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            "use '.remote()'."
        )


def remote(*args, **kwargs):
    """Decorator: turn a function into a RemoteFunction or a class into an
    ActorClass.  Supports bare ``@remote`` and parameterized
    ``@remote(num_cpus=..., num_chips=...)``."""

    def make(obj):
        res = _normalize_resources(
            kwargs.get("num_cpus"),
            kwargs.get("num_chips"),
            kwargs.get("resources"),
            is_actor=isinstance(obj, type),
        )
        if isinstance(obj, type):
            return ActorClass(obj, res)
        return RemoteFunction(obj, res)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return make(args[0])
    if args:
        raise TypeError("@remote accepts only keyword arguments")
    return make


def kill(handle: ActorHandle, no_restart: bool = True):
    ctx = rt.current_worker()
    if ctx is not None:
        ctx.send(("kill_actor", handle._actor_id))
        return
    rt.get_runtime().kill_actor(handle._actor_id, no_restart=no_restart)
