"""Module-level object-plane API: put / get / wait.

Parity surface (SURVEY.md §1-L1): ``ray.put`` (Overview_of_Ray.ipynb:cc-34),
``ray.get`` (cc-44), ``ray.wait`` (Scaling_batch_inference.ipynb:cc-115).
Works from both driver and worker processes — the store is shared memory, so
both sides read/write it directly.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from . import runtime as rt
from .object_store import ObjectRef


def put(value: Any) -> ObjectRef:
    ctx = rt.current_worker()
    if ctx is not None:
        return ctx.store.put(value)
    return rt.get_runtime().put(value)


def get(ref, timeout: Optional[float] = None):
    ctx = rt.current_worker()
    if ctx is not None:
        if isinstance(ref, list):
            return [get(r, timeout) for r in ref]
        if not isinstance(ref, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(ref)}")
        return rt._resolve_if_error(ctx.store.get(ref.id, timeout=timeout))
    return rt.get_runtime().get(ref, timeout=timeout)


def nodes() -> List[dict]:
    """Cluster membership with heartbeat liveness (``ray.nodes()`` analog),
    served by the C++ GCS control plane that ``init()`` starts by default
    (SURVEY.md §3.6: ray.init() always runs GCS on the head node)."""
    return rt.get_runtime().nodes()


def wait(refs: List[ObjectRef], num_returns: int = 1, timeout: Optional[float] = None):
    ctx = rt.current_worker()
    if ctx is None:
        return rt.get_runtime().wait(refs, num_returns=num_returns, timeout=timeout)
    if not isinstance(refs, list):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns may not exceed len(refs)")
    deadline = None if timeout is None else time.monotonic() + timeout
    ready, pending = [], list(refs)
    while len(ready) < num_returns:
        still = []
        for r in pending:
            (ready if ctx.store.contains(r.id) else still).append(r)
        pending = still
        if len(ready) >= num_returns:
            break
        if deadline is not None and time.monotonic() >= deadline:
            break
        time.sleep(0.001)
    return ready, pending
