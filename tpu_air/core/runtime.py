"""tpu_air core runtime: tasks, actors, objects over host processes.

This is the TPU-native counterpart of the reference stack's Ray Core layer
(raylet + GCS + core_worker, SURVEY.md §1-L1/§2B), collapsed for a single-host
control domain into one driver-side scheduler plus a pool of persistent worker
processes:

* **tasks** — stateless remote functions (``@tpu_air.remote`` on a function,
  Overview_of_Ray.ipynb:cc-41), executed on any idle worker with enough
  resources;
* **actors** — stateful remote classes (Scaling_batch_inference.ipynb:cc-105),
  each pinned to a dedicated worker process, method calls executed FIFO;
* **objects** — immutable values in the shared-memory store
  (object_store.py); every task/actor result is sealed there and resolved by
  ``get``/``wait`` exactly like ``ray.get``/``ray.wait``
  (Overview_of_Ray.ipynb:cc-44, Scaling_batch_inference.ipynb:cc-115).

Scheduling resources are **CPUs and TPU chips** (not GPUs): an actor asking
for ``num_chips=k`` receives a lease of k physical chip ids, exported to its
process as ``TPU_AIR_CHIP_IDS`` so the parallel layer can build the matching
sub-mesh (SURVEY.md §2B raylet row: "placement = sub-mesh assignment").

Workers may themselves submit tasks / create actors (nested ``.remote``):
control messages ride the worker⇄driver pipe up to the scheduler, and results
always come back through the object store, so there is a single data plane.
"""

from __future__ import annotations

import itertools
import os
import secrets
import sys
import tempfile
import threading
import time
import traceback
import multiprocessing as mp
import multiprocessing.connection as mpc
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .object_store import ObjectRef, ObjectStore, new_object_id

# airtrace propagation (stdlib-only module; the observability package pulls
# in nothing heavy at import time)
from tpu_air.faults import plan as _faults
from tpu_air.observability import tracing as _tracing

# --------------------------------------------------------------------------
# errors
# --------------------------------------------------------------------------


class TpuAirError(Exception):
    pass


class RemoteError(TpuAirError):
    """A task/actor method raised; carries the remote traceback and, when
    the failed call was traced, the trace id (``/api/traces?trace_id=...``
    answers "which hop killed this request")."""

    def __init__(self, cause_repr: str, remote_traceback: str,
                 trace_id: Optional[str] = None):
        super().__init__(f"{cause_repr}\n\n--- remote traceback ---\n{remote_traceback}")
        self.cause_repr = cause_repr
        self.remote_traceback = remote_traceback
        self.trace_id = trace_id


class ActorDiedError(TpuAirError):
    pass


class ChipLease(list):
    """A granted chip lease: a ``list`` of physical chip ids (drop-in for
    the plain ``List[int]`` existing callers index, join, and pass back to
    :meth:`Runtime.release_chips`) plus revocation plumbing for preemptible
    capacity.

    Real TPU preemption arrives with *notice*: the infrastructure says
    "these chips go away in N seconds", and a holder that drains or
    migrates within the window loses nothing.  The handle models exactly
    that: :meth:`on_revoke` registers a callback; when the lease is
    revoked (by the ``runtime.lease`` fault site's ``notice`` action or by
    :meth:`Runtime.revoke_lease`), every callback fires once with the
    advance warning in seconds, and ``notice_s`` seconds later the lease
    reports :attr:`expired` — past that point the holder must treat the
    chips as gone.

    Callbacks run on the revoker's thread and never under the handle's
    lock; a callback registered *after* the notice was delivered fires
    immediately (no lost-wakeup window between engine construction and
    watcher registration).
    """

    def __init__(self, chip_ids):
        super().__init__(chip_ids)
        self._lease_lock = threading.Lock()
        self._callbacks: List[Any] = []
        self._notice_s: Optional[float] = None
        self._expired = threading.Event()

    @property
    def chip_ids(self) -> List[int]:
        return list(self)

    @property
    def revoking(self) -> bool:
        """True once a revocation notice has been delivered."""
        with self._lease_lock:
            return self._notice_s is not None

    @property
    def notice_s(self) -> Optional[float]:
        """The advance warning the notice carried, or None if not revoked."""
        with self._lease_lock:
            return self._notice_s

    @property
    def expired(self) -> bool:
        """True once the notice window has elapsed: the chips are gone."""
        return self._expired.is_set()

    def on_revoke(self, callback) -> None:
        """Register ``callback(notice_s: float)`` to fire when this lease
        is revoked.  Fires immediately (on the caller's thread) if the
        notice already arrived."""
        with self._lease_lock:
            if self._notice_s is None:
                self._callbacks.append(callback)
                return
            notice = self._notice_s
        callback(notice)

    def deliver_notice(self, notice_s: float) -> None:
        """Deliver the revocation notice: fire callbacks with ``notice_s``
        of warning, then mark the lease expired once the window elapses.
        Idempotent — only the first delivery counts."""
        notice = max(0.0, float(notice_s))
        with self._lease_lock:
            if self._notice_s is not None:
                return
            self._notice_s = notice
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(notice)
            except Exception:  # a broken callback must not mask the notice
                pass
        if notice > 0:
            t = threading.Timer(notice, self._expired.set)
            t.daemon = True
            t.start()
        else:
            self._expired.set()

    def wait_expired(self, timeout: Optional[float] = None) -> bool:
        return self._expired.wait(timeout)

    def __reduce__(self):
        # a lease crossing a process boundary (spmd closures pickled to
        # host agents) degrades to its chip ids — the revocation plumbing
        # (lock, timer, callbacks) is meaningful only in the driver that
        # holds the lease
        return (list, (list(self),))


class _ErrorSentinel:
    """Stored in the object store in place of a result when a task fails."""

    def __init__(self, cause_repr: str, tb: str, trace_id: Optional[str] = None):
        self.cause_repr = cause_repr
        self.tb = tb
        self.trace_id = trace_id

    def raise_(self):
        raise RemoteError(self.cause_repr, self.tb,
                          trace_id=getattr(self, "trace_id", None))


def _resolve_if_error(value):
    if isinstance(value, _ErrorSentinel):
        value.raise_()
    return value


# --------------------------------------------------------------------------
# specs / messages
# --------------------------------------------------------------------------

_INLINE_LIMIT = 512 * 1024  # payloads larger than this travel via the store


@dataclass
class _TaskSpec:
    task_id: str            # also the result object id
    payload: Optional[bytes]  # cloudpickle of (fn, args, kwargs); None if via store
    payload_ref: Optional[str]
    resources: Dict[str, float]
    kind: str = "task"      # "task" | "actor_create" | "actor_task"
    actor_id: Optional[str] = None
    method: Optional[str] = None
    from_worker: bool = False
    # airtrace carrier captured at submit time (None unless the submitting
    # thread had tracing on and an active span — the zero-cost-off default)
    trace_ctx: Optional[Dict[str, str]] = None


@dataclass
class _WorkerState:
    worker_id: int
    proc: mp.process.BaseProcess
    conn: mpc.Connection
    busy_task: Optional[str] = None
    actor_id: Optional[str] = None   # set => dedicated actor worker
    alive: bool = True


@dataclass
class _ActorState:
    actor_id: str
    worker: _WorkerState
    name: Optional[str]
    chip_ids: List[int] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    dead: bool = False
    pending: int = 0


# --------------------------------------------------------------------------
# worker process
# --------------------------------------------------------------------------

_worker_ctx: Optional["_WorkerContext"] = None


class _WorkerContext:
    """Per-worker client handle back to the driver scheduler."""

    def __init__(self, conn: mpc.Connection, store: ObjectStore, worker_id: int):
        self.conn = conn
        self.store = store
        self.worker_id = worker_id
        self.send_lock = threading.Lock()

    def send(self, msg):
        with self.send_lock:
            self.conn.send(msg)


def current_worker() -> Optional["_WorkerContext"]:
    return _worker_ctx


def _store_result(store: ObjectStore, object_id: str, fn, args, kwargs):
    try:
        result = fn(*args, **kwargs)
        store.put(result, object_id)
        return True
    except BaseException as e:  # noqa: BLE001 - remote boundary
        store.put(
            _ErrorSentinel(repr(e), traceback.format_exc(),
                           trace_id=_tracing.current_trace_id()),
            object_id,
        )
        return False


def _send_done(worker_id: int, task_id: str) -> None:
    """Send the task-complete control message, piggybacking any spans this
    worker recorded since the last done (engine spans, nested task spans) so
    the driver's recorder sees one merged timeline.  The common untraced
    case ships the plain 3-tuple."""
    spans = _tracing.drain_if_any()
    if spans is None:
        _worker_ctx.send(("done", worker_id, task_id))
    else:
        _worker_ctx.send(("done", worker_id, task_id, spans))


def _load_payload(store: ObjectStore, spec: dict):
    blob = spec["payload"]
    if blob is None:
        blob = store.get(spec["payload_ref"])
    return serialization.loads(blob)


def _resolve_args(store: ObjectStore, args, kwargs):
    def r(v):
        return store.get(v.id) if isinstance(v, ObjectRef) else v

    args = [r(a) for a in args]
    kwargs = {k: r(v) for k, v in kwargs.items()}
    for v in itertools.chain(args, kwargs.values()):
        _resolve_if_error(v)
    return args, kwargs


def _worker_main(
    worker_id: int,
    store_root: str,
    conn: mpc.Connection,
    driver_env: Optional[Dict[str, str]] = None,
):
    global _worker_ctx
    if driver_env:
        # apply the driver's environ as of spawn time (forkserver children
        # otherwise see the env snapshot from forkserver start) — must happen
        # before any jax backend init reads JAX_PLATFORMS/XLA_FLAGS
        for k, v in driver_env.items():
            os.environ[k] = v
        for k in list(os.environ):
            if k not in driver_env:
                os.environ.pop(k, None)
    # the tracing flag (and any installed fault plan) was read at import
    # time, which for forkserver children predates the env application
    # above — re-read both
    _tracing._sync_from_env()
    _faults._sync_from_env()
    store = ObjectStore(store_root)
    _worker_ctx = _WorkerContext(conn, store, worker_id)
    actors: Dict[str, Any] = {}
    failed_actors: Dict[str, _ErrorSentinel] = {}
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        kind = msg[0]
        if kind == "shutdown":
            return
        spec = msg[1]
        if kind == "task":
            fn, args, kwargs = _load_payload(store, spec)
            try:
                args, kwargs = _resolve_args(store, args, kwargs)
            except RemoteError as e:
                store.put(_ErrorSentinel(repr(e), e.remote_traceback), spec["task_id"])
                _send_done(worker_id, spec["task_id"])
                continue
            name = getattr(fn, "__name__", None) or "task"
            with _tracing.task_span(f"task.{name}", spec.get("trace_ctx")) as sp:
                if not _store_result(store, spec["task_id"], fn, args, kwargs):
                    sp.set_status("error")
            _send_done(worker_id, spec["task_id"])
        elif kind == "actor_create":
            chip_ids = spec.get("chip_ids") or []
            if chip_ids:
                # Export the chip lease so the parallel layer (mesh.py) builds
                # this actor's sub-mesh from exactly these devices.
                os.environ["TPU_AIR_CHIP_IDS"] = ",".join(str(c) for c in chip_ids)
            else:
                # a chip-LESS actor must not inherit a lease from the parent
                # env (e.g. forked mid-SPMD-fit while the driver holds the
                # cluster lease in its own environ)
                os.environ.pop("TPU_AIR_CHIP_IDS", None)
            cls, args, kwargs = _load_payload(store, spec)
            args, kwargs = _resolve_args(store, args, kwargs)
            cname = getattr(cls, "__name__", None) or "actor"
            with _tracing.task_span(f"actor.{cname}.__init__",
                                    spec.get("trace_ctx")) as sp:
                if not _store_result(store, spec["task_id"], cls, args, kwargs):
                    sp.set_status("error")
            # fetch back so a failed __init__ is visible to callers
            inst = store.get(spec["task_id"])
            if isinstance(inst, _ErrorSentinel):
                failed_actors[spec["actor_id"]] = inst
            else:
                actors[spec["actor_id"]] = inst
            _send_done(worker_id, spec["task_id"])
        elif kind == "actor_task":
            inst = actors.get(spec["actor_id"])
            _, args, kwargs = _load_payload(store, spec)
            if inst is None:
                init_err = failed_actors.get(spec["actor_id"])
                store.put(
                    init_err
                    if init_err is not None
                    else _ErrorSentinel("ActorDiedError('actor failed to initialize')", ""),
                    spec["task_id"],
                )
            else:
                try:
                    args, kwargs = _resolve_args(store, args, kwargs)
                    method = getattr(inst, spec["method"])
                except RemoteError as e:
                    store.put(_ErrorSentinel(repr(e), e.remote_traceback), spec["task_id"])
                    _send_done(worker_id, spec["task_id"])
                    continue
                name = f"actor.{type(inst).__name__}.{spec['method']}"
                with _tracing.task_span(name, spec.get("trace_ctx")) as sp:
                    if not _store_result(store, spec["task_id"], method, args, kwargs):
                        sp.set_status("error")
            _send_done(worker_id, spec["task_id"])


# --------------------------------------------------------------------------
# driver-side runtime
# --------------------------------------------------------------------------

_STALE_SESSION_AGE_S = 2 * 3600.0


def _kill_quietly(proc) -> None:
    try:
        proc.kill()
    except (OSError, ProcessLookupError):
        pass


def _sweep_stale_sessions(base: str, spill_base: str = "/var/tmp") -> None:
    """Remove store dirs leaked by killed sessions (tmpfs is RAM — leaks
    accumulate).  A dir is stale when untouched for _STALE_SESSION_AGE_S.
    ``spill_base`` is injectable for tests."""
    now = time.time()
    names = []
    for d in (base, spill_base):  # spill_base: spill dirs of killed sessions
        try:
            names += [(d, n) for n in os.listdir(d)]
        except OSError:
            pass
    for d, name in names:
        if not name.startswith(("tpu_air-", "tpu_air-spill-")):
            continue
        if d == spill_base and not name.startswith("tpu_air-spill-"):
            continue
        path = os.path.join(d, name)
        try:
            if name.startswith("tpu_air-spill-"):
                # a spill dir's mtime goes stale while its session still
                # runs (spills may all happen early) — it is reapable only
                # once the owning store root is gone.  The dir carries an
                # ``.owner`` marker naming the root's absolute path
                # (ObjectStore._ensure_spill_dir), so liveness is checked
                # against THAT path — a custom-base root named tpu_air-*
                # is not mistaken for dead just because it isn't under a
                # default base.  No marker (pre-marker sessions): fall back
                # to probing the default bases, and never sweep owners that
                # aren't tpu_air-* (they live somewhere we can't check).
                owner_root = None
                try:
                    with open(os.path.join(path, ".owner")) as f:
                        owner_root = f.read().strip()
                except OSError:
                    pass
                if owner_root:
                    if os.path.exists(owner_root):
                        continue
                else:
                    owner = name[len("tpu_air-spill-"):]
                    if not owner.startswith("tpu_air-"):
                        continue
                    if any(
                        os.path.exists(os.path.join(b, owner))
                        for b in ("/dev/shm", tempfile.gettempdir())
                    ):
                        continue
            if now - os.path.getmtime(path) < _STALE_SESSION_AGE_S:
                continue
            for f in os.listdir(path):
                try:
                    os.chmod(os.path.join(path, f), 0o644)
                    os.remove(os.path.join(path, f))
                except OSError:
                    pass
            os.rmdir(path)
        except OSError:
            pass


class Runtime:
    """Driver-side scheduler + control plane (the GCS/raylet analog)."""

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        num_chips: Optional[int] = None,
        start_method: Optional[str] = None,
        store_root: Optional[str] = None,
    ):
        self.session_id = secrets.token_hex(8)
        base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
        _sweep_stale_sessions(base)
        self.store_root = store_root or os.path.join(base, f"tpu_air-{self.session_id}")
        self.store = ObjectStore(self.store_root, create=True)
        self.num_cpus = num_cpus if num_cpus is not None else max(2, os.cpu_count() or 2)
        if num_chips is None:
            num_chips = int(os.environ.get("TPU_AIR_NUM_CHIPS", "0") or 0)
        self.num_chips = num_chips
        # Topology for lease SHAPES (docs/MULTIHOST.md §2): chip g lives on
        # host g // chips_per_host.  Single host (the default) degenerates to
        # chips_per_host == num_chips and the shape policy is a no-op.
        cph = int(os.environ.get("TPU_AIR_CHIPS_PER_HOST", "0") or 0)
        self.chips_per_host = cph if 0 < cph <= num_chips else (num_chips or 1)
        self.free_chips: List[int] = list(range(self.num_chips))
        self.avail = {"cpu": float(self.num_cpus), "chip": float(self.num_chips)}
        method = start_method or os.environ.get("TPU_AIR_START_METHOD", "fork")
        self.mp_ctx = mp.get_context(method)
        self._fs_ctx = None  # lazy preloaded forkserver (see _pick_ctx)
        self.lock = threading.RLock()
        self.workers: Dict[int, _WorkerState] = {}
        self.actors: Dict[str, _ActorState] = {}
        self.named_actors: Dict[str, str] = {}
        self.task_resources: Dict[str, Dict[str, float]] = {}
        self.task_worker: Dict[str, int] = {}
        # task_id -> trace id, for traced tasks only: lets worker-death
        # sentinels carry the trace id of the request they killed
        self.task_trace: Dict[str, str] = {}
        self.queue: List[_TaskSpec] = []
        # Actor creations wait in their own FIFO queue for resources (chip
        # leases especially) instead of spin-waiting in the caller — an
        # oversubscribed Tune sweep queues its trials rather than timing out
        # (SURVEY.md §7 hard-part 1; Model_finetuning…ipynb:cc-53-54).
        self.actor_queue: List[dict] = []
        self.pending_actors: Dict[str, dict] = {}          # queued, not yet placed
        self.pending_actor_tasks: Dict[str, List[_TaskSpec]] = {}
        # Event-driven wait(): notified whenever a result object may have
        # been sealed (task done / worker death / driver put).
        self._obj_cv = threading.Condition()
        self._next_worker_id = itertools.count()
        self._stop = threading.Event()
        self._wakeup_r, self._wakeup_w = mp.Pipe(duplex=False)
        # Worker-process spawns (forkserver first spin-up imports jax/pandas,
        # seconds) run on a dedicated placement thread so the listener thread
        # never blocks — done/submit messages from all workers must keep
        # flowing while an actor is being placed.
        self._placement_event = threading.Event()
        self._spawn_requests = 0
        self._to_spawn: List[tuple] = []  # claimed creations awaiting spawn
        self._placement_thread = threading.Thread(
            target=self._placement_loop, daemon=True
        )
        self._placement_thread.start()
        self._listener = threading.Thread(target=self._listen, daemon=True)
        self._listener.start()
        # GCS control plane on the DEFAULT path: reference ray.init() always
        # runs GCS on the head node (SURVEY.md §3.6, Install_locally.md:58-64),
        # so single-host runs get the same membership / actor-directory /
        # liveness machinery as multi-host instead of a dark control plane.
        self.node_id = f"host-{os.environ.get('TPU_AIR_PROCESS_ID', '0')}"
        self.gcs_address: Optional[str] = None
        self._gcs_proc = None
        self._gcs_heartbeat = None
        self._gcs_client = None
        self._gcs_lock = threading.Lock()
        if os.environ.get("TPU_AIR_NO_GCS", "0") != "1":
            self._start_gcs()
        self._min_idle = min(2, self.num_cpus)
        for _ in range(self._min_idle):
            self._spawn_worker()

    # -- GCS control plane ---------------------------------------------------
    def _start_gcs(self):
        """Start (or join) the C++ control-plane daemon.  Best-effort: a
        missing protobuf toolchain degrades to ``gcs_address=None`` and every
        directory call becomes a no-op."""
        existing = os.environ.get("TPU_AIR_GCS")
        if existing:
            # multi-host member / local-cluster child: join the cluster's
            # daemon — membership/heartbeat already owned by the
            # distributed layer (spawn_local_cluster / host agents)
            self.gcs_address = existing
            return
        from tpu_air.control import client as _gcs_mod

        if os.path.exists(os.path.join(_gcs_mod._NATIVE, "tpu_air_gcs")):
            self._launch_gcs_daemon()  # binary ready: ~ms, synchronous
        else:
            # first use on a fresh checkout: build.sh (protoc + C++) can take
            # minutes — init() must not block on it; the control plane comes
            # up late and everything degrades gracefully until then
            threading.Thread(
                target=self._launch_gcs_daemon, daemon=True,
                name="tpu_air-gcs-build",
            ).start()

    def _launch_gcs_daemon(self):
        try:
            import atexit

            from tpu_air.control import HeartbeatThread, start_gcs

            proc, port = start_gcs(dead_after_ms=3000)
            if self._stop.is_set():  # runtime shut down mid-build
                proc.kill()
                return
            # airlint: disable=CC001 — builder-thread publish vs shutdown
            # read: the _stop check above plus the atexit kill below close
            # the race (worst case the daemon dies at exit, not shutdown)
            self._gcs_proc = proc
            # the daemon must not outlive this process even when an
            # exception skips shutdown(): an orphan daemon holds the
            # inherited stderr pipe open, wedging any parent reading it
            atexit.register(_kill_quietly, proc)
            # airlint: disable=CC001 — best-effort control plane: readers
            # treat a not-yet-published address as None and no-op
            self.gcs_address = f"127.0.0.1:{port}"
            self._gcs("register_node", self.node_id, address="",
                      num_chips=self.num_chips)
            # airlint: disable=CC001 — shutdown may miss a heartbeat that
            # starts mid-build; the thread is daemonic and its daemon is
            # killed at exit anyway
            self._gcs_heartbeat = HeartbeatThread(
                self.gcs_address, self.node_id, interval=0.5,
                num_chips=self.num_chips,
            )
            self._gcs_heartbeat.start()
        except Exception as e:  # noqa: BLE001 — control plane is best-effort
            print(f"tpu_air: gcs control plane unavailable: {e}", file=sys.stderr)
            self.gcs_address = None

    def _gcs(self, method: str, *args, **kwargs):
        """Resilient GCS RPC: reconnect on failure (the daemon may restart),
        never raise into the scheduler.  The client is shared across the
        listener/placement/driver threads — create/teardown under a lock so
        one thread can't close a socket another is about to use."""
        if self.gcs_address is None:
            return None
        with self._gcs_lock:
            try:
                if self._gcs_client is None:
                    from tpu_air.control import GcsClient

                    self._gcs_client = GcsClient(self.gcs_address)
                return getattr(self._gcs_client, method)(*args, **kwargs)
            except (ConnectionError, OSError, RuntimeError):
                if self._gcs_client is not None:
                    self._gcs_client.close()
                self._gcs_client = None
                return None

    def nodes(self) -> List[Dict]:
        """Cluster membership with heartbeat liveness, from the control plane
        (``ray.nodes()`` analog).  [] when the GCS is unavailable."""
        return self._gcs("list_nodes") or []

    # -- worker management -------------------------------------------------
    def _pick_ctx(self):
        """fork is fast, but forking after a JAX/XLA backend is live in this
        process inherits dead compiler threadpools → child deadlocks on its
        first jax op.  Once a backend exists, switch to a preloaded
        FORKSERVER: the server process imports the heavy module graph once
        (worker_preload.py — jax/pandas/numpy, no backend init) and children
        fork from it in ~10ms, vs ~3s of re-imports per spawn worker."""
        if self.mp_ctx.get_start_method() == "fork":
            xb = sys.modules.get("jax._src.xla_bridge")
            if xb is not None and getattr(xb, "_backends", None):
                if self._fs_ctx is None:
                    # NB: the forkserver is a process-global singleton; the
                    # preload applies to any other forkserver user in this
                    # process, and if one is already running the preload is
                    # silently skipped (workers then pay the imports — slower,
                    # still correct).  Env snapshot staleness is handled by
                    # shipping the driver's current environ with each worker
                    # (_spawn_worker) and applying it in _worker_main before
                    # any backend init.
                    ctx = mp.get_context("forkserver")
                    ctx.set_forkserver_preload(["tpu_air.core.worker_preload"])
                    self._fs_ctx = ctx
                return self._fs_ctx
        return self.mp_ctx

    def _spawn_worker(self, actor_id: Optional[str] = None) -> _WorkerState:
        wid = next(self._next_worker_id)
        parent, child = mp.Pipe(duplex=True)
        # Ship the driver's CURRENT environ: forkserver children inherit the
        # env frozen at server start, so vars set since (JAX_PLATFORMS,
        # multi-host contract, …) must be re-applied in the worker before it
        # initializes any backend.
        proc = self._pick_ctx().Process(
            target=_worker_main,
            args=(wid, self.store_root, child, dict(os.environ)),
            daemon=True,
            name=f"tpu_air-worker-{wid}",
        )
        proc.start()
        child.close()
        ws = _WorkerState(worker_id=wid, proc=proc, conn=parent, actor_id=actor_id)
        with self.lock:
            self.workers[wid] = ws
        self._poke_listener()
        return ws

    def _poke_listener(self):
        try:
            self._wakeup_w.send(b"x")
        except OSError:
            pass

    # -- listener thread ----------------------------------------------------
    def _listen(self):
        while not self._stop.is_set():
            with self.lock:
                conns = [w.conn for w in self.workers.values() if w.alive]
                conn_owner = {id(w.conn): w for w in self.workers.values() if w.alive}
            ready = mpc.wait(conns + [self._wakeup_r], timeout=0.2)
            for conn in ready:
                if conn is self._wakeup_r:
                    try:
                        self._wakeup_r.recv()
                    except (EOFError, OSError):
                        pass
                    continue
                owner = conn_owner.get(id(conn))
                if owner is None:
                    continue
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    self._on_worker_death(owner)
                    continue
                try:
                    self._handle_msg(owner, msg)
                except Exception:  # noqa: BLE001 - listener must survive
                    traceback.print_exc(file=sys.stderr)

    def _handle_msg(self, worker: _WorkerState, msg):
        kind = msg[0]
        if kind == "done":
            _, wid, task_id = msg[:3]
            # traced tasks piggyback their worker-side spans on the done
            # message; fold them into the driver recorder so /api/traces
            # serves one merged timeline
            if len(msg) > 3 and msg[3]:
                _tracing.recorder().record_many(msg[3])
            with self.lock:
                res = self.task_resources.pop(task_id, None)
                self.task_worker.pop(task_id, None)
                self.task_trace.pop(task_id, None)
                if res:
                    self._release(res)
                if worker.busy_task == task_id:
                    worker.busy_task = None
                st = self.actors.get(worker.actor_id) if worker.actor_id else None
                if st:
                    st.pending = max(0, st.pending - 1)
            self._notify_objects()
            self._schedule()
        elif kind == "submit":
            spec = _TaskSpec(**msg[1])
            spec.from_worker = True
            if spec.trace_ctx:
                with self.lock:
                    self.task_trace[spec.task_id] = spec.trace_ctx["trace_id"]
            self._enqueue(spec)
        elif kind == "create_actor":
            # Non-blocking: the creation queues for resources in _schedule.
            self._create_actor(**msg[1], from_worker=True)
        elif kind == "actor_call":
            spec = _TaskSpec(**msg[1])
            spec.from_worker = True
            if spec.trace_ctx:
                with self.lock:
                    self.task_trace[spec.task_id] = spec.trace_ctx["trace_id"]
            self._submit_actor_task_spec(spec)
        elif kind == "kill_actor":
            self.kill_actor(msg[1], no_restart=True)

    def _on_worker_death(self, worker: _WorkerState):
        crashed_traces = []
        with self.lock:
            worker.alive = False
            outstanding = [
                t for t, wid in self.task_worker.items() if wid == worker.worker_id
            ]
            for task_id in outstanding:
                self.task_worker.pop(task_id, None)
                res = self.task_resources.pop(task_id, None)
                if res:
                    self._release(res)
                if not self.store.contains(task_id):
                    trace_id = self.task_trace.pop(task_id, None)
                    if trace_id:
                        crashed_traces.append(trace_id)
                    self.store.put(  # airlint: disable=CC003 — chaos-only: the fault-plan delay inside put models the slow-disk stall this bounded error-sentinel write already risks under the lock; zero cost with no plan installed
                        _ErrorSentinel(
                            f"WorkerCrashed(worker={worker.worker_id})",
                            "worker process died while executing this task",
                            trace_id=trace_id,
                        ),
                        task_id,
                    )
            dead_actor = None
            if worker.actor_id and worker.actor_id in self.actors:
                st = self.actors[worker.actor_id]
                # st.dead means kill_actor already released the claim — a
                # killed worker's pipe-close lands here too, and releasing
                # twice inflates avail until free_chips.pop underflows
                if not st.dead:
                    st.dead = True
                    dead_actor = worker.actor_id
                    if st.name:
                        self.named_actors.pop(st.name, None)
                    # release the FULL claim (cpu + chip), exactly like
                    # kill_actor — chip avail comes back via st.resources,
                    # the physical ids via free_chips
                    self._release(st.resources)
                    st.resources = {}
                    self.free_chips.extend(st.chip_ids)
                    st.chip_ids = []
            self.workers.pop(worker.worker_id, None)
        if dead_actor:
            self._gcs("mark_actor_dead", dead_actor)
        # flight recorder (outside the lock: dump() scrapes snapshot()/
        # engine_stats(), which re-take it); no-op unless
        # TPU_AIR_POSTMORTEM_DIR is set, and dump() never raises
        from tpu_air.observability import postmortem as _postmortem

        if _postmortem.enabled():
            _postmortem.dump(
                f"WorkerCrashed(worker={worker.worker_id})",
                {
                    "worker_id": worker.worker_id,
                    "pid": worker.proc.pid,
                    "actor_id": worker.actor_id,
                    "busy_task": worker.busy_task,
                    "outstanding_tasks": outstanding,
                    "trace_ids": crashed_traces,
                },
            )
        self._notify_objects()
        self._schedule()

    # -- resources ----------------------------------------------------------
    def _can_fit(self, res: Dict[str, float]) -> bool:
        return all(self.avail.get(k, 0.0) >= v for k, v in res.items())

    def _claim_chips(
        self, n: int, exclude_hosts: frozenset = frozenset()
    ) -> Optional[List[int]]:
        """Topology-aware chip-lease allocation (docs/MULTIHOST.md §2).

        Shapes: a lease of ``n <= chips_per_host`` chips lives entirely on
        ONE host (best-fit: the feasible host with the fewest free chips, so
        big leases aren't starved by fragmentation); a larger lease is built
        from WHOLE free hosts (contiguous host range preferred — the induced
        mesh's collectives then ride ICI), so it is always a contiguous
        sub-slice rather than an arbitrary k-subset.  Returns None when the
        request doesn't tile the free topology right now (caller keeps it
        queued, FIFO).  ``exclude_hosts``: hosts reserved for an earlier
        shape-blocked request in the queue (see ``_claim_queued_actors``) —
        their free chips are invisible to this claim.  Caller holds the
        lock.
        """
        if n == 0:
            return []
        cph = self.chips_per_host
        by_host: Dict[int, List[int]] = {}
        for c in sorted(self.free_chips):
            if c // cph not in exclude_hosts:
                by_host.setdefault(c // cph, []).append(c)
        if n <= cph:
            fitting = [h for h, f in by_host.items() if len(f) >= n]
            if not fitting:
                return None
            host = min(fitting, key=lambda h: (len(by_host[h]), h))
            ids = by_host[host][:n]
        else:
            if n % cph != 0:
                return None
            k = n // cph
            full = sorted(h for h, f in by_host.items() if len(f) == cph)
            if len(full) < k:
                return None
            # prefer a contiguous run of k hosts; fall back to any k full
            # hosts (documented relaxation — strict contiguity could wedge
            # a sweep forever on a fragmented slice)
            chosen = None
            for i in range(len(full) - k + 1):
                if full[i + k - 1] - full[i] == k - 1:
                    chosen = full[i : i + k]
                    break
            if chosen is None:
                chosen = full[:k]
            ids = [c for h in chosen for c in by_host[h]]
        for c in ids:
            self.free_chips.remove(c)
        return ids

    def _acquire(self, res: Dict[str, float]):
        for k, v in res.items():
            self.avail[k] = self.avail.get(k, 0.0) - v

    def _release(self, res: Dict[str, float]):
        for k, v in res.items():
            self.avail[k] = self.avail.get(k, 0.0) + v

    def _reserve_closest(self, nchips: int, reserved: set) -> None:
        """Reserve the hosts a shape-blocked request is closest to
        recombining (the whole free hosts for a multi-host span; the
        freest host for a single-host lease).  Shared by the real queue
        scan and its ``_queued_reservations`` simulation.  Caller holds
        the lock; mutates ``reserved`` in place."""
        cph = self.chips_per_host
        free_by_host: Dict[int, int] = {}
        for c in self.free_chips:
            h = c // cph
            if h not in reserved:
                free_by_host[h] = free_by_host.get(h, 0) + 1
        if nchips > cph:
            need = nchips // cph
            whole = sorted(h for h, f in free_by_host.items() if f == cph)
            if whole:
                # Some whole hosts are free: reserve only those.  Partial
                # hosts stay unreserved on purpose — smaller shape-blocked
                # requests behind this head reserve them for themselves
                # (see test_lease_stress.py), which transitively protects
                # the recombination capacity without this head hoarding it.
                reserved.update(whole[:need])
            else:
                # ZERO whole hosts free: reserve the hosts with the MOST
                # free chips — the ones closest to recombining into whole
                # hosts — mirroring the single-host branch.  Without this,
                # a stream of 1-chip leases behind a shape-blocked
                # multi-host span could keep nibbling partially-free hosts
                # and no host would ever become whole (ADVICE r5
                # starvation).
                partial = sorted(
                    free_by_host, key=lambda h: (-free_by_host[h], h)
                )
                reserved.update(partial[:need])
        elif free_by_host:
            reserved.add(max(free_by_host, key=lambda h: (free_by_host[h], -h)))

    def _queued_reservations(self) -> set:
        """Hosts queued actor requests are entitled to, per the same FIFO
        scan ``_claim_queued_actors`` runs — simulated claim-free (feasible
        requests consume chips from a scratch copy of the free list;
        shape-blocked ones reserve recombination hosts; the scan stops at
        the first count-infeasible head, like the real one).  Driver-level
        ``lease_chips`` consults this so it can neither nibble capacity a
        shape-blocked queued request is waiting to recombine NOR outrace a
        feasible queue head (a simulated claim reserves its hosts whole —
        slightly broader than the claim itself, which only costs the
        driver one extra 50 ms poll).  Caller holds the lock."""
        saved = list(self.free_chips)
        avail = dict(self.avail)
        reserved: set = set()
        try:
            for rec in self.actor_queue:
                if not all(avail.get(kk, 0.0) >= vv
                           for kk, vv in rec["resources"].items()):
                    break
                nchips = int(rec["resources"].get("chip", 0))
                ids = self._claim_chips(nchips, frozenset(reserved))
                if ids is None:
                    self._reserve_closest(nchips, reserved)
                else:
                    for kk, vv in rec["resources"].items():
                        avail[kk] = avail.get(kk, 0.0) - vv
                    reserved.update(c // self.chips_per_host for c in ids)
        finally:
            self.free_chips = saved
        return reserved

    def lease_chips(self, n: int, timeout: Optional[float] = None) -> ChipLease:
        """Driver-level chip lease (shape-aware, docs/MULTIHOST.md §2) for
        runs that execute on the driver itself rather than in an actor —
        the SPMD-multihost trainer path.  Blocks until a correctly-shaped
        lease frees up, honoring the hosts reserved for queued actor
        requests (``_queued_reservations``) so driver leases cannot starve
        a shape-blocked queue head.  Returns a :class:`ChipLease` (a list
        of chip ids carrying ``on_revoke`` preemption plumbing).  Pair
        with :meth:`release_chips`."""
        self._check_satisfiable({"chip": float(n)})
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ids = None
            with self.lock:
                if self._can_fit({"chip": float(n)}):
                    ids = self._claim_chips(
                        n, frozenset(self._queued_reservations()))
                    if ids is not None:
                        self._acquire({"chip": float(n)})
            if ids is not None:
                lease = ChipLease(ids)
                if _faults.enabled():
                    try:
                        spec = _faults.perturb("runtime.lease", key=str(n))
                    except _faults.LeaseRevokedError:
                        # the claim must not leak: hand the chips back
                        # before surfacing the revocation
                        self.release_chips(ids)
                        raise
                    if spec is not None and spec.action == "notice":
                        # graceful preemption: grant the lease, then
                        # delay_s later deliver notice_s of warning via
                        # the handle (preemption lands mid-work, not at
                        # acquisition)
                        t = threading.Timer(
                            spec.delay_s, lease.deliver_notice,
                            args=(spec.notice_s,))
                        t.daemon = True
                        t.start()
                return lease
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"no {n}-chip lease available after {timeout}s")
            time.sleep(0.05)

    def revoke_lease(self, lease: ChipLease, notice_s: float = 0.0) -> None:
        """Programmatic preemption: deliver a revocation notice to a lease
        this runtime granted.  The holder's ``on_revoke`` callbacks fire
        with ``notice_s`` of warning; the holder still calls
        :meth:`release_chips` when its drain completes (or the driver
        reclaims on expiry)."""
        lease.deliver_notice(notice_s)

    def release_chips(self, chip_ids: List[int]) -> None:
        with self.lock:
            self._release({"chip": float(len(chip_ids))})
            self.free_chips.extend(chip_ids)
        self._schedule()

    def _check_satisfiable(self, res: Dict[str, float]):
        total = {"cpu": float(self.num_cpus), "chip": float(self.num_chips)}
        for k, v in res.items():
            if v > total.get(k, 0.0):
                raise TpuAirError(
                    f"resource request {res} exceeds cluster total {total}"
                )
        nchips = int(res.get("chip", 0))
        if nchips > self.chips_per_host and nchips % self.chips_per_host != 0:
            raise TpuAirError(
                f"chip lease of {nchips} spans hosts and must be a multiple "
                f"of chips_per_host={self.chips_per_host} (whole-host lease "
                "shapes, docs/MULTIHOST.md)"
            )

    # -- task submission -----------------------------------------------------
    def _pack_payload(self, payload_tuple) -> Tuple[Optional[bytes], Optional[str]]:
        blob = serialization.dumps(payload_tuple)
        if len(blob) <= _INLINE_LIMIT:
            return blob, None
        ref = self.store.put(blob)
        return None, ref.id

    def submit_task(self, fn, args, kwargs, resources: Dict[str, float],
                    trace_ctx: Optional[Dict[str, str]] = None) -> ObjectRef:
        if _faults.enabled():
            _faults.perturb(
                "runtime.task", key=getattr(fn, "__name__", "") or "")
        self._check_satisfiable(resources)
        task_id = new_object_id()
        payload, payload_ref = self._pack_payload((fn, args, kwargs))
        spec = _TaskSpec(task_id, payload, payload_ref, resources,
                         trace_ctx=trace_ctx)
        if trace_ctx:
            with self.lock:
                self.task_trace[task_id] = trace_ctx["trace_id"]
        self._enqueue(spec)
        return ObjectRef(task_id)

    def _enqueue(self, spec: _TaskSpec):
        with self.lock:
            self.queue.append(spec)
        self._schedule()

    def _placement_loop(self):
        """Dedicated thread for anything that spawns worker processes:
        queued-actor placement and deadlock-avoidance spawns.  Fed by
        ``_placement_event`` from ``_schedule`` (which may run on the
        listener thread and must never block on a process spawn)."""
        while not self._stop.is_set():
            self._placement_event.wait(timeout=0.2)
            if self._stop.is_set():
                return
            self._placement_event.clear()
            try:
                self._place_queued_actors()
                with self.lock:
                    n = self._spawn_requests
                    self._spawn_requests = 0
                for _ in range(n):
                    self._spawn_worker()
                if n:
                    self._schedule()  # fresh workers can take queued tasks
            except Exception:  # noqa: BLE001 - placement must survive
                traceback.print_exc(file=sys.stderr)

    def _schedule(self):
        spawn_needed = 0
        # claim actor resources FIRST (fast, synchronous) so queued tasks
        # can't outrace a queued actor lease; only the spawn is deferred
        self._claim_queued_actors()
        with self.lock:
            remaining: List[_TaskSpec] = []
            idle = [
                w
                for w in self.workers.values()
                if w.alive and w.busy_task is None and w.actor_id is None
            ]
            for spec in self.queue:
                if not idle or not self._can_fit(spec.resources):
                    remaining.append(spec)
                    continue
                worker = idle.pop()
                self._acquire(spec.resources)
                self.task_resources[spec.task_id] = spec.resources
                self.task_worker[spec.task_id] = worker.worker_id
                worker.busy_task = spec.task_id
                worker.conn.send(
                    (
                        "task",
                        {
                            "task_id": spec.task_id,
                            "payload": spec.payload,
                            "payload_ref": spec.payload_ref,
                            "trace_ctx": spec.trace_ctx,
                        },
                    )
                )
            self.queue = remaining
            stuck = [s for s in remaining if self._can_fit(s.resources)]
            if stuck and not idle:
                # Grow the pool toward num_cpus for ANY dispatchable queued
                # task: the initial pool is only min(2, num_cpus), and
                # without growth driver-submitted parallelism stays capped
                # at 2 workers regardless of num_cpus (the W9 20-parallel-
                # tasks contract needs the full width).  Workers persist
                # once spawned, so this converges after the first burst.
                pool = sum(
                    1 for w in self.workers.values()
                    if w.alive and w.actor_id is None
                )
                headroom = max(0, int(self.num_cpus) - pool)
                # Deadlock avoidance: a worker blocked on a nested task's
                # result occupies its process slot, so nested submissions
                # get fresh workers (beyond num_cpus if needed) when the
                # pool is saturated.
                nested = sum(1 for s in stuck if s.from_worker)
                # cap each spawn burst: _placement_loop re-runs _schedule
                # after the burst, so already-spawned workers start taking
                # tasks between bursts instead of idling behind a serial
                # spawn of num_cpus processes
                spawn_needed = min(len(stuck), max(headroom, nested), 4)
        if spawn_needed:
            with self.lock:
                self._spawn_requests = max(self._spawn_requests, spawn_needed)
            self._placement_event.set()

    # -- actors --------------------------------------------------------------
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        resources: Dict[str, float],
        name: Optional[str] = None,
        trace_ctx: Optional[Dict[str, str]] = None,
    ) -> Tuple[str, ObjectRef]:
        actor_id = new_object_id()
        ready_id = new_object_id()
        payload, payload_ref = self._pack_payload((cls, args, kwargs))
        self._create_actor(
            actor_id=actor_id,
            ready_id=ready_id,
            payload=payload,
            payload_ref=payload_ref,
            resources=resources,
            name=name,
            trace_ctx=trace_ctx,
        )
        return actor_id, ObjectRef(ready_id)

    def _create_actor(
        self,
        actor_id: str,
        ready_id: str,
        payload,
        payload_ref,
        resources: Dict[str, float],
        name: Optional[str],
        from_worker: bool = False,
        trace_ctx: Optional[Dict[str, str]] = None,
    ):
        try:
            self._check_satisfiable(resources)
        except TpuAirError:
            if not from_worker:
                raise
            # worker-originated creation: surface the error through the ready ref
            self.store.put(
                _ErrorSentinel(f"resource request {resources} unsatisfiable", ""),
                ready_id,
            )
            self._notify_objects()
            return
        # Actors hold their resources for their whole lifetime; creation
        # QUEUES for them (FIFO) like a task rather than spin-waiting in the
        # caller — an oversubscribed sweep waits its turn instead of timing
        # out (SURVEY.md §7 hard-part 1).
        rec = {
            "actor_id": actor_id,
            "ready_id": ready_id,
            "payload": payload,
            "payload_ref": payload_ref,
            "resources": resources,
            "name": name,
            "trace_ctx": trace_ctx,
        }
        if trace_ctx:
            with self.lock:
                self.task_trace[ready_id] = trace_ctx["trace_id"]
        with self.lock:
            self.actor_queue.append(rec)
            self.pending_actors[actor_id] = rec
        self._schedule()

    def _claim_queued_actors(self):
        """FAST phase, runs synchronously inside ``_schedule`` (any thread):
        claim resources for queued actor creations that now fit, FIFO with
        one carve-out — if the head's chip COUNT doesn't fit, later (smaller)
        requests do NOT jump it (strict FIFO, so a big lease can't be starved
        by a stream of small ones), but a head whose count fits while no
        valid lease SHAPE exists (e.g. 4 chips free as 2+2 across hosts
        cannot serve a 4-chip single-host lease) is scanned PAST, so
        fragmentation cannot stall unrelated work indefinitely.

        Starvation bound for the skipped request: it RESERVES the hosts
        closest to satisfying its shape (the currently-whole free hosts for
        a multi-host span; the freest host for a single-host lease), and
        requests behind it in the queue cannot claim chips on reserved
        hosts — so a stream of small leases can consume fragments, never
        the capacity the blocked request is waiting to recombine.
        Reservations are recomputed on every pass in FIFO order, so the
        moment a feasible shape exists the blocked request (scanned first,
        with nothing reserved against it) claims before anything behind it.
        Because the claim happens before ``_schedule`` dispatches tasks, a
        stream of chip tasks cannot outrace a queued chip lease either.
        The slow process spawn is handed to the placement thread via
        ``_to_spawn``."""
        claimed = False
        with self.lock:
            reserved: set = set()
            i = 0
            while i < len(self.actor_queue):
                rec = self.actor_queue[i]
                if not self._can_fit(rec["resources"]):
                    break
                nchips = int(rec["resources"].get("chip", 0))
                chip_ids = self._claim_chips(nchips, frozenset(reserved))
                if chip_ids is None:
                    # shape-blocked: reserve the hosts this request is
                    # closest to recombining, then keep scanning
                    self._reserve_closest(nchips, reserved)
                    i += 1
                    continue
                self.actor_queue.pop(i)
                self._acquire(rec["resources"])
                self._to_spawn.append((rec, chip_ids))
                claimed = True
        if claimed:
            self._placement_event.set()

    def _place_queued_actors(self):
        """SLOW phase (placement thread only): spawn a worker process for
        each claimed creation and register the actor."""
        while True:
            with self.lock:
                if not self._to_spawn:
                    return
                rec, chip_ids = self._to_spawn.pop(0)
            try:
                worker = self._spawn_worker(actor_id=rec["actor_id"])
            except Exception as e:  # noqa: BLE001 - spawn failure (EAGAIN/OOM)
                # the claim already happened — it MUST be rolled back and the
                # ready ref resolved, or callers blocked on the actor (some
                # deliberately without timeout) hang forever on a leaked lease
                with self.lock:
                    self._release(rec["resources"])
                    self.free_chips.extend(chip_ids)
                    self.pending_actors.pop(rec["actor_id"], None)
                    buffered = self.pending_actor_tasks.pop(rec["actor_id"], [])
                sentinel = _ErrorSentinel(
                    f"ActorPlacementFailed(actor={rec['actor_id']})",
                    f"worker spawn failed: {type(e).__name__}: {e}",
                )
                # resolve the ready ref AND every method call buffered while
                # the actor was queued — a caller blocked (often without
                # timeout) on a buffered call must not hang forever
                for tid in [rec["ready_id"]] + [s.task_id for s in buffered]:
                    self.store.put(sentinel, tid)
                self._notify_objects()
                continue
            with self.lock:
                if rec.get("cancelled") or self._stop.is_set():
                    # kill_actor() cancelled this creation while we were
                    # spawning (lock released around the process spawn), or
                    # the runtime is shutting down and must not register a
                    # worker after shutdown() cleared the table — undo the
                    # placement so nothing leaks
                    self._release(rec["resources"])
                    self.free_chips.extend(chip_ids)
                    worker.alive = False
                    self.workers.pop(worker.worker_id, None)
                    try:
                        worker.conn.send(("shutdown",))
                    except OSError:
                        pass
                    continue
                actor_id, ready_id = rec["actor_id"], rec["ready_id"]
                st = _ActorState(actor_id, worker, rec["name"], chip_ids, rec["resources"])
                self.actors[actor_id] = st
                if rec["name"]:
                    self.named_actors[rec["name"]] = actor_id
                worker.busy_task = ready_id
                st.pending += 1
                self.task_resources[ready_id] = {}
                self.task_worker[ready_id] = worker.worker_id
                worker.conn.send(
                    (
                        "actor_create",
                        {
                            "task_id": ready_id,
                            "payload": rec["payload"],
                            "payload_ref": rec["payload_ref"],
                            "actor_id": actor_id,
                            "chip_ids": chip_ids,
                            "trace_ctx": rec.get("trace_ctx"),
                        },
                    )
                )
                # Flush method calls buffered while the actor was queued
                # BEFORE leaving pending state, all under the lock: a
                # concurrent direct submit must not reach the worker pipe
                # ahead of earlier buffered calls (per-caller FIFO).
                for spec in self.pending_actor_tasks.pop(actor_id, []):
                    st.pending += 1
                    self.task_resources[spec.task_id] = {}
                    self.task_worker[spec.task_id] = worker.worker_id
                    worker.conn.send(
                        (
                            "actor_task",
                            {
                                "task_id": spec.task_id,
                                "payload": spec.payload,
                                "payload_ref": spec.payload_ref,
                                "actor_id": spec.actor_id,
                                "method": spec.method,
                                "trace_ctx": spec.trace_ctx,
                            },
                        )
                    )
                self.pending_actors.pop(actor_id, None)
            # publish to the GCS actor directory (outside the lock: localhost
            # RPC, best-effort, must never stall the placement thread's lock)
            self._gcs("register_actor", actor_id, node_id=self.node_id,
                      name=rec["name"] or "", chip_ids=list(chip_ids))

    def submit_actor_task(self, actor_id, method, args, kwargs,
                          trace_ctx: Optional[Dict[str, str]] = None) -> ObjectRef:
        task_id = new_object_id()
        payload, payload_ref = self._pack_payload((None, args, kwargs))
        spec = _TaskSpec(
            task_id, payload, payload_ref, {}, kind="actor_task",
            actor_id=actor_id, method=method, trace_ctx=trace_ctx,
        )
        if trace_ctx:
            with self.lock:
                self.task_trace[task_id] = trace_ctx["trace_id"]
        self._submit_actor_task_spec(spec)
        return ObjectRef(task_id)

    def _submit_actor_task_spec(self, spec: _TaskSpec):
        with self.lock:
            if spec.actor_id in self.pending_actors:
                # actor is still queued for resources — buffer the call
                self.pending_actor_tasks.setdefault(spec.actor_id, []).append(spec)
                return
            st = self.actors.get(spec.actor_id)
            if st is None or st.dead or not st.worker.alive:
                self.store.put(  # airlint: disable=CC003 — chaos-only: the fault-plan delay inside put models the slow-disk stall this bounded error-sentinel write already risks under the lock; zero cost with no plan installed
                    _ErrorSentinel(
                        f"ActorDiedError(actor={spec.actor_id})", "",
                        trace_id=(spec.trace_ctx or {}).get("trace_id"),
                    ),
                    spec.task_id,
                )
                self._notify_objects()
                return
            st.pending += 1
            self.task_resources[spec.task_id] = {}
            self.task_worker[spec.task_id] = st.worker.worker_id
            try:
                st.worker.conn.send(
                    (
                        "actor_task",
                        {
                            "task_id": spec.task_id,
                            "payload": spec.payload,
                            "payload_ref": spec.payload_ref,
                            "actor_id": spec.actor_id,
                            "method": spec.method,
                            "trace_ctx": spec.trace_ctx,
                        },
                    )
                )
            except OSError:
                # the worker died between the liveness check and the send
                # (broken pipe before the listener reaps it) — resolve the
                # call as actor death instead of leaking an OSError into
                # the caller (serve failover keys off ActorDiedError); the
                # listener's death path does the full cleanup when it lands
                st.pending -= 1
                self.task_resources.pop(spec.task_id, None)
                self.task_worker.pop(spec.task_id, None)
                self.store.put(  # airlint: disable=CC003 — chaos-only: the fault-plan delay inside put models the slow-disk stall this bounded error-sentinel write already risks under the lock; zero cost with no plan installed
                    _ErrorSentinel(
                        f"ActorDiedError(actor={spec.actor_id})",
                        "worker pipe broken at submit",
                        trace_id=(spec.trace_ctx or {}).get("trace_id"),
                    ),
                    spec.task_id,
                )
                self._notify_objects()

    def actor_pending_placement(self, actor_id: str) -> bool:
        """True while the actor's creation is still queued for resources
        (no lease claimed yet).  Once False, the actor owns its lease and
        only construction time separates it from serving calls."""
        with self.lock:
            return any(r["actor_id"] == actor_id for r in self.actor_queue)

    def crash_actor(self, actor_id: str) -> bool:
        """Hard-kill an actor's worker process with NO bookkeeping — unlike
        :meth:`kill_actor` there is no shutdown message, no join, and no
        resource release here.  The listener thread discovers the corpse via
        pipe EOF and runs the real ``_on_worker_death`` path, which is
        exactly what fault injection needs: a crash indistinguishable from
        an involuntary one.  Returns False if the actor is unknown/dead."""
        with self.lock:
            st = self.actors.get(actor_id)
            if st is None or st.dead:
                return False
            proc = st.worker.proc
        _kill_quietly(proc)
        return True

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        with self.lock:
            rec = self.pending_actors.pop(actor_id, None)
            if rec is not None:
                # Still queued (or mid-placement) — cancel.  The cancelled
                # flag covers the race where _place_queued_actors already
                # popped the record and is spawning the worker: it checks the
                # flag under the lock before registering and rolls back.
                rec["cancelled"] = True
                self.actor_queue = [r for r in self.actor_queue if r["actor_id"] != actor_id]
                buffered = self.pending_actor_tasks.pop(actor_id, [])
                for tid in [rec["ready_id"]] + [s.task_id for s in buffered]:
                    self.store.put(  # airlint: disable=CC003 — chaos-only: the fault-plan delay inside put models the slow-disk stall this bounded error-sentinel write already risks under the lock; zero cost with no plan installed
                        _ErrorSentinel(f"ActorDiedError(actor={actor_id})", ""), tid
                    )
                self._notify_objects()
                return
            st = self.actors.get(actor_id)
            if st is None or st.dead:  # already released (double-kill / crash)
                return
            st.dead = True
            if st.name:
                self.named_actors.pop(st.name, None)
            self._release(st.resources)
            st.resources = {}
            self.free_chips.extend(st.chip_ids)
            st.chip_ids = []
            worker = st.worker
            worker.alive = False
            self.workers.pop(worker.worker_id, None)
        self._gcs("mark_actor_dead", actor_id)
        try:
            worker.conn.send(("shutdown",))
        except OSError:
            pass
        worker.proc.join(timeout=2)
        if worker.proc.is_alive():
            worker.proc.terminate()
        self._schedule()  # freed chips/cpus may place queued actors

    # -- object plane ---------------------------------------------------------
    def _notify_objects(self):
        with self._obj_cv:
            self._obj_cv.notify_all()

    def put(self, value) -> ObjectRef:
        ref = self.store.put(value)
        self._notify_objects()
        return ref

    def get(self, ref, timeout: Optional[float] = None):
        if isinstance(ref, list):
            return [self.get(r, timeout) for r in ref]
        if not isinstance(ref, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(ref)}")
        return _resolve_if_error(self.store.get(ref.id, timeout=timeout))

    def wait(self, refs, num_returns=1, timeout=None):
        if not isinstance(refs, list):
            raise TypeError("wait() expects a list of ObjectRefs")
        if num_returns > len(refs):
            raise ValueError("num_returns may not exceed len(refs)")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while True:
            still = []
            for r in pending:
                if self.store.contains(r.id):
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            # Event-driven: task completions / worker deaths / driver puts
            # notify _obj_cv, so the hot ray.wait load-balance loop (W7,
            # Scaling_batch_inference.ipynb:cc-115) wakes with no poll
            # latency.  The 50ms cap covers objects sealed out-of-band
            # (e.g. a worker's own store.put with no control message).
            slot = 0.05
            if deadline is not None:
                slot = min(slot, max(deadline - time.monotonic(), 0.0))
            with self._obj_cv:
                self._obj_cv.wait(timeout=slot)
        return ready, pending

    # -- lifecycle -------------------------------------------------------------
    def shutdown(self):
        self._stop.set()
        self._placement_event.set()  # wake the placement thread to exit
        self._poke_listener()
        self._listener.join(timeout=2)
        self._placement_thread.join(timeout=2)
        with self.lock:
            workers = list(self.workers.values())
            self.workers.clear()
            self.actors.clear()
        for w in workers:
            try:
                w.conn.send(("shutdown",))
            except OSError:
                pass
        for w in workers:
            w.proc.join(timeout=1)
            if w.proc.is_alive():
                w.proc.terminate()
        if self._gcs_heartbeat is not None:
            self._gcs_heartbeat.stop()
        # airlint: disable=CC001 — shutdown-time teardown: _gcs() holds
        # _gcs_lock for create/use and tolerates a concurrently closed
        # client (reconnect-or-None path), so an unlocked read is safe here
        if self._gcs_client is not None:
            self._gcs_client.close()
            self._gcs_client = None
        if self._gcs_proc is not None:
            self._gcs_proc.kill()
            self._gcs_proc = None
        self.store.destroy()


# --------------------------------------------------------------------------
# module-level singleton API
# --------------------------------------------------------------------------

_runtime: Optional[Runtime] = None


def init(
    num_cpus: Optional[int] = None,
    num_chips: Optional[int] = None,
    ignore_reinit_error: bool = True,
    include_dashboard: Optional[bool] = None,
    dashboard_port: int = 8265,
    **kwargs,
) -> Runtime:
    """Start the tpu_air runtime (the ``ray.init()`` analog,
    Install_locally.md:58-64). Idempotent by default.

    ``include_dashboard=True`` starts the status service at
    127.0.0.1:<dashboard_port> and prints the URL — the reference's
    "Follow the link … to open the Ray Dashboard" flow
    (Model_finetuning…ipynb:cc-9).  Default off (None) to keep tests quiet;
    set env TPU_AIR_DASHBOARD=1 to default on.
    """
    global _runtime
    if _runtime is not None:
        if not ignore_reinit_error:
            raise TpuAirError("tpu_air.init() called twice")
        if include_dashboard:  # honor an explicit request on reinit too
            _start_dashboard(dashboard_port)
        return _runtime
    # multi-host rendezvous first (no-op unless the TPU_AIR_COORDINATOR env
    # contract is set): after this, jax sees the global device list and this
    # process knows its rank (SURVEY.md §3.6 "initialize the multi-host
    # runtime on every host")
    try:
        from tpu_air.parallel import distributed as _dist

        _dist.ensure_initialized()
    except Exception as e:  # rendezvous failure must not mask the local path
        print(f"tpu_air: multi-host rendezvous failed: {e}", file=sys.stderr)
    _runtime = Runtime(num_cpus=num_cpus, num_chips=num_chips, **kwargs)
    if include_dashboard is None:
        include_dashboard = os.environ.get("TPU_AIR_DASHBOARD", "0") == "1"
    if include_dashboard:
        _start_dashboard(dashboard_port)
    return _runtime


def _start_dashboard(port: int) -> None:
    try:
        from tpu_air.observability import start_dashboard

        url = start_dashboard(port=port)
        print(f"tpu_air dashboard: {url}")
    except OSError as e:
        print(f"tpu_air dashboard failed to start: {e}")


def is_initialized() -> bool:
    return _runtime is not None


def shutdown():
    global _runtime
    if _runtime is not None:
        try:
            from tpu_air.observability import stop_dashboard

            stop_dashboard()
        except Exception:  # noqa: BLE001 — shutdown is best-effort; dashboard may never have started
            pass
        _runtime.shutdown()
        _runtime = None


def get_runtime() -> Runtime:
    """Return the active runtime, auto-initializing like Ray does on first
    ``.remote()`` call."""
    if _runtime is None:
        init()
    return _runtime


def attach_chip_lease(chip_ids: Optional[List[int]] = None) -> ChipLease:
    """ACTOR-side lease attachment: wrap the chips this process was placed
    on (``TPU_AIR_CHIP_IDS``, set by the worker loop at task start, or an
    explicit ``chip_ids``) in a :class:`ChipLease` so in-actor holders —
    the serving engine, a training step — get the same ``on_revoke``
    preemption surface as driver-side :meth:`Runtime.lease_chips` holders.

    Consults the ``runtime.lease`` fault site exactly like the driver
    path, with one difference: a cold ``revoke`` here delivers an
    immediate zero-notice revocation through the handle instead of
    raising — the actor is already *placed* on the chips, so the
    interesting failure is losing them mid-work, not failing to get
    them."""
    if chip_ids is None:
        raw = os.environ.get("TPU_AIR_CHIP_IDS", "")
        chip_ids = [int(c) for c in raw.split(",") if c.strip()]
    lease = ChipLease(chip_ids)
    if _faults.enabled():
        try:
            # keyed by the PHYSICAL chip ids so a plan's ``match`` can aim
            # a preemption at the replica holding a specific chip
            spec = _faults.perturb(
                "runtime.lease",
                key="chips=" + ",".join(str(c) for c in lease),
            )
        except _faults.LeaseRevokedError:
            spec = None
            lease.deliver_notice(0.0)
        if spec is not None and spec.action == "notice":
            t = threading.Timer(spec.delay_s, lease.deliver_notice,
                                args=(spec.notice_s,))
            t.daemon = True
            t.start()
    return lease
