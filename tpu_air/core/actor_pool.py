"""ActorPool — load-balanced fan-out over a fixed set of actors.

Parity target: ``ray.util.actor_pool.ActorPool`` with ``map``/``map_unordered``
(Scaling_batch_inference.ipynb:cc-124,127,129) plus the submit/get_next
protocol.  Internally this is the same idle-actor/``wait`` recycling loop the
reference teaches by hand at Scaling_batch_inference.ipynb:cc-115.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List

from .api import get, wait
from .object_store import ObjectRef
from .remote import ActorHandle


class ActorPool:
    def __init__(self, actors: List[ActorHandle]):
        if not actors:
            raise ValueError("ActorPool requires at least one actor")
        self._idle: List[ActorHandle] = list(actors)
        self._future_to_actor: Dict[ObjectRef, ActorHandle] = {}
        self._index_to_future: Dict[int, ObjectRef] = {}
        self._next_task_index = 0
        self._next_return_index = 0

    # -- low-level protocol -------------------------------------------------
    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def submit(self, fn: Callable[[ActorHandle, Any], ObjectRef], value: Any):
        if not self._idle:
            raise RuntimeError("no idle actors; call get_next first")
        actor = self._idle.pop(0)
        future = fn(actor, value)
        self._future_to_actor[future] = actor
        self._index_to_future[self._next_task_index] = future
        self._next_task_index += 1

    def get_next(self, timeout=None):
        """Next result in submission order."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        result = get(future, timeout=timeout)
        self._return_actor(future)
        return result

    def get_next_unordered(self, timeout=None):
        """Next result to complete, any order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        for idx, fut in list(self._index_to_future.items()):
            if fut == future:
                del self._index_to_future[idx]
                break
        result = get(future)
        self._return_actor(future)
        return result

    def _return_actor(self, future: ObjectRef):
        actor = self._future_to_actor.pop(future)
        self._idle.append(actor)

    def push(self, actor: ActorHandle):
        """Add an idle actor to the pool (autoscaling hook — the data plane
        grows a map_batches pool under backlog, Scaling_batch_inference.
        ipynb:cc-4 'autoscaling the actor pool')."""
        self._idle.append(actor)

    def size(self) -> int:
        return len(self._idle) + len(self._future_to_actor)

    # -- high-level map -----------------------------------------------------
    def map(self, fn, values: Iterable[Any]) -> Iterator[Any]:
        values = list(values)
        sent = 0
        while sent < len(values) and self.has_free():
            self.submit(fn, values[sent])
            sent += 1
        for _ in range(len(values)):
            result = self.get_next()
            if sent < len(values):
                self.submit(fn, values[sent])
                sent += 1
            yield result

    def map_unordered(self, fn, values: Iterable[Any]) -> Iterator[Any]:
        values = list(values)
        sent = 0
        while sent < len(values) and self.has_free():
            self.submit(fn, values[sent])
            sent += 1
        for _ in range(len(values)):
            result = self.get_next_unordered()
            if sent < len(values):
                self.submit(fn, values[sent])
                sent += 1
            yield result
