"""Serialization for the tpu_air object plane.

The reference stack serializes task args/returns with pickle5 + out-of-band
buffers so large numpy/Arrow payloads move without copies (Ray core_worker,
SURVEY.md §2B "plasma").  We reproduce that contract in pure Python: values are
cloudpickled with protocol 5, out-of-band ``PickleBuffer`` payloads are
concatenated after a small header, and deserialization can reconstruct the
buffers either as copies (bytes) or as zero-copy views over an ``mmap``.

Wire format::

    [u64 npickle][u32 nbuf][u64 len_0]...[u64 len_{nbuf-1}][pickle][buf_0]...

All integers little-endian.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

_HDR = struct.Struct("<QI")
_LEN = struct.Struct("<Q")


def serialize(value: Any) -> List[memoryview | bytes]:
    """Serialize ``value`` into a list of chunks suitable for writev-style IO."""
    buffers: List[pickle.PickleBuffer] = []
    payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    chunks: List[memoryview | bytes] = []
    raw = [b.raw() for b in buffers]
    header = bytearray(_HDR.pack(len(payload), len(raw)))
    for mv in raw:
        header += _LEN.pack(mv.nbytes)
    chunks.append(bytes(header))
    chunks.append(payload)
    chunks.extend(raw)
    return chunks


def serialized_nbytes(chunks: List[memoryview | bytes]) -> int:
    return sum(c.nbytes if isinstance(c, memoryview) else len(c) for c in chunks)


def deserialize(buf, zero_copy: bool = True) -> Any:
    """Deserialize from a buffer (bytes / memoryview / mmap).

    With ``zero_copy=True`` the out-of-band buffers are memoryview slices of
    ``buf`` — the caller must keep ``buf`` alive for the lifetime of the value
    (the object store pins the mmap on the value via a finalizer).
    """
    return deserialize_ex(buf, zero_copy=zero_copy)[0]


def _parse_wire(buf) -> Tuple[memoryview, List[memoryview]]:
    """Split a wire-format buffer into (pickle payload, oob piece views)."""
    mv = memoryview(buf)
    npickle, nbuf = _HDR.unpack_from(mv, 0)
    off = _HDR.size
    lens: List[int] = []
    for _ in range(nbuf):
        (n,) = _LEN.unpack_from(mv, off)
        lens.append(n)
        off += _LEN.size
    payload = mv[off : off + npickle]
    off += npickle
    pieces: List[memoryview] = []
    for n in lens:
        pieces.append(mv[off : off + n])
        off += n
    return payload, pieces


def deserialize_ex(buf, zero_copy: bool = True) -> Tuple[Any, int]:
    """Like :func:`deserialize`, also returning the out-of-band buffer count.

    ``nbuf == 0`` means the value is fully self-contained (no views into
    ``buf``) — the object store uses this to release its read pin
    immediately instead of tying it to the value's lifetime."""
    payload, pieces = _parse_wire(buf)
    oob = pieces if zero_copy else [p.tobytes() for p in pieces]
    return pickle.loads(payload, buffers=oob), len(pieces)


def deserialize_pinned(buf) -> Tuple[Any, List[Any]]:
    """Zero-copy deserialize returning weakref-able out-of-band holders.

    Each out-of-band piece is wrapped in a uint8 ndarray *holder* and the
    holders are handed to ``pickle.loads`` as the buffers.  Anything pickle
    reconstructs over a piece keeps its holder alive through the
    buffer-protocol chain (reconstructed array → base memoryview → exporter
    = holder), including objects later *derived* from the value — a Series
    pulled out of a DataFrame, an array extracted from a dict.  A resource
    pinned until every returned holder is garbage therefore outlives every
    object that can still reach the underlying bytes, which a finalizer on
    the top-level value alone cannot guarantee.
    """
    import numpy as np

    payload, pieces = _parse_wire(buf)
    holders = [np.frombuffer(p, dtype=np.uint8) for p in pieces]
    return pickle.loads(payload, buffers=holders), holders


def dumps(value: Any) -> bytes:
    """One-shot contiguous serialization (control-plane messages)."""
    out = bytearray()
    for c in serialize(value):
        out += c
    return bytes(out)


def loads(data: bytes) -> Any:
    return deserialize(data, zero_copy=False)
