"""Serialization for the tpu_air object plane.

The reference stack serializes task args/returns with pickle5 + out-of-band
buffers so large numpy/Arrow payloads move without copies (Ray core_worker,
SURVEY.md §2B "plasma").  We reproduce that contract in pure Python: values are
cloudpickled with protocol 5, out-of-band ``PickleBuffer`` payloads are
concatenated after a small header, and deserialization can reconstruct the
buffers either as copies (bytes) or as zero-copy views over an ``mmap``.

Wire format::

    [u64 npickle][u32 nbuf][u64 len_0]...[u64 len_{nbuf-1}][pickle][buf_0]...

All integers little-endian.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

_HDR = struct.Struct("<QI")
_LEN = struct.Struct("<Q")


def serialize(value: Any) -> List[memoryview | bytes]:
    """Serialize ``value`` into a list of chunks suitable for writev-style IO."""
    buffers: List[pickle.PickleBuffer] = []
    payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    chunks: List[memoryview | bytes] = []
    raw = [b.raw() for b in buffers]
    header = bytearray(_HDR.pack(len(payload), len(raw)))
    for mv in raw:
        header += _LEN.pack(mv.nbytes)
    chunks.append(bytes(header))
    chunks.append(payload)
    chunks.extend(raw)
    return chunks


def serialized_nbytes(chunks: List[memoryview | bytes]) -> int:
    return sum(c.nbytes if isinstance(c, memoryview) else len(c) for c in chunks)


def deserialize(buf, zero_copy: bool = True) -> Any:
    """Deserialize from a buffer (bytes / memoryview / mmap).

    With ``zero_copy=True`` the out-of-band buffers are memoryview slices of
    ``buf`` — the caller must keep ``buf`` alive for the lifetime of the value
    (the object store pins the mmap on the value via a finalizer).
    """
    return deserialize_ex(buf, zero_copy=zero_copy)[0]


def deserialize_ex(buf, zero_copy: bool = True) -> Tuple[Any, int]:
    """Like :func:`deserialize`, also returning the out-of-band buffer count.

    ``nbuf == 0`` means the value is fully self-contained (no views into
    ``buf``) — the object store uses this to release its read pin
    immediately instead of tying it to the value's lifetime."""
    mv = memoryview(buf)
    npickle, nbuf = _HDR.unpack_from(mv, 0)
    off = _HDR.size
    lens: List[int] = []
    for _ in range(nbuf):
        (n,) = _LEN.unpack_from(mv, off)
        lens.append(n)
        off += _LEN.size
    payload = mv[off : off + npickle]
    off += npickle
    oob: List[Any] = []
    for n in lens:
        piece = mv[off : off + n]
        oob.append(piece if zero_copy else piece.tobytes())
        off += n
    return pickle.loads(payload, buffers=oob), nbuf


def dumps(value: Any) -> bytes:
    """One-shot contiguous serialization (control-plane messages)."""
    out = bytearray()
    for c in serialize(value):
        out += c
    return bytes(out)


def loads(data: bytes) -> Any:
    return deserialize(data, zero_copy=False)
