"""Forkserver preload set.

Worker processes are forked from a forkserver that has already imported the
heavy module graph below (jax's import alone is ~2s; pandas ~0.7s), so each
worker starts in ~10ms instead of paying the imports again — the reason a
BatchPredictor actor pool can spin up in milliseconds once the driver holds
a live jax backend (fork would inherit dead XLA threadpools; spawn would
re-import everything).

IMPORTANT: modules only — nothing here may initialize a jax backend or touch
devices; children initialize their own backends on first use.
"""
# airlint: disable-file=RT003 — every preload import is optional: a failure
# here only means the worker pays that import lazily on first use

try:  # noqa: SIM105
    import numpy  # noqa: F401
except Exception:
    pass
try:
    import pandas  # noqa: F401
except Exception:
    pass
try:
    import jax  # noqa: F401
except Exception:
    pass
try:
    import sklearn.ensemble  # noqa: F401  (GBDT workloads, W8/W9)
except Exception:
    pass
try:
    import tpu_air.core.runtime  # noqa: F401
except Exception:
    pass
