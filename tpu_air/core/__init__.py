"""tpu_air.core — the task/actor/object runtime (L1)."""

from .actor_pool import ActorPool
from .api import get, nodes, put, wait
from .object_store import ObjectRef
from .remote import ActorClass, ActorHandle, ActorMethod, RemoteFunction, kill, remote
from .runtime import (
    ActorDiedError,
    RemoteError,
    Runtime,
    TpuAirError,
    get_runtime,
    init,
    is_initialized,
    shutdown,
)

__all__ = [
    "ActorClass",
    "ActorDiedError",
    "ActorHandle",
    "ActorMethod",
    "ActorPool",
    "ObjectRef",
    "RemoteError",
    "RemoteFunction",
    "Runtime",
    "TpuAirError",
    "get",
    "get_runtime",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
]
