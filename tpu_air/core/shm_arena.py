"""Python wrapper over the C++ shared-memory arena store (_native/store.cpp).

The arena is one mmap'd file in the store directory shared by every process
on the host.  The C++ library owns layout, the atomic index, and the bump
allocator; this wrapper maps the same file and moves the payload bytes —
writes go straight into shared memory, reads come back as memoryview slices
of the mapping (zero-copy both ways).
"""

from __future__ import annotations

import hashlib
import mmap
import os
from typing import List, Optional, Tuple


def _key(object_id: str) -> bytes:
    """Fixed 32-byte arena key for an arbitrary-length object id.  The C
    index stores exactly 32 key bytes; hashing (rather than truncating)
    keeps ids like '{trial_id}-report-{seq}' collision-free."""
    return hashlib.sha256(object_id.encode()).digest()


class Arena:
    """Handle to the shared arena for this process."""

    def __init__(self, path: str, create: bool = False,
                 capacity: Optional[int] = None, slots: int = 1 << 16):
        from tpu_air import _native

        self._lib = _native.load_store_lib()
        self.path = path
        if create and not os.path.exists(path):
            capacity = capacity or int(
                os.environ.get("TPU_AIR_ARENA_BYTES", str(256 << 20))
            )
            rc = self._lib.arena_create(path.encode(), capacity, slots)
            if rc not in (0,) and not os.path.exists(path):
                raise OSError(f"arena_create failed: {rc}")
        if not os.path.exists(path):
            # fail fast: missing file means no arena for this store (ENOENT
            # is not the transient "creator still initializing" case)
            raise FileNotFoundError(path)
        # a concurrent creator may still be initializing (magic is written
        # last, release-ordered) — retry briefly before giving up
        import time

        self._h = -1
        for _ in range(50):
            self._h = self._lib.arena_open(path.encode())
            if self._h >= 0 or not os.path.exists(path):
                break
            time.sleep(0.01)
        if self._h < 0:
            raise OSError(f"arena_open({path}) failed: {self._h}")
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, os.path.getsize(path))
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)

    # -- write --------------------------------------------------------------
    def put_chunks(self, object_id: str, chunks: List) -> bool:
        """Write chunks for object_id into the arena. False = no space /
        duplicate (caller falls back to the file store)."""
        bid = _key(object_id)
        total = sum(c.nbytes if isinstance(c, memoryview) else len(c) for c in chunks)
        off = self._lib.arena_alloc(self._h, bid, total)
        if off < 0:
            return False
        pos = int(off)
        for c in chunks:
            b = bytes(c) if not isinstance(c, (bytes, bytearray, memoryview)) else c
            n = b.nbytes if isinstance(b, memoryview) else len(b)
            self._view[pos : pos + n] = b
            pos += n
        if self._lib.arena_seal(self._h, bid) != 0:
            return False
        return True

    # -- read ---------------------------------------------------------------
    def lookup(self, object_id: str) -> Optional[memoryview]:
        """Zero-copy view of a sealed object, or None.  UNPINNED — valid
        only while the object is not deleted (use for contains/peek)."""
        import ctypes

        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.arena_lookup(
            self._h, _key(object_id), ctypes.byref(off), ctypes.byref(size)
        )
        if rc != 1:
            return None
        # read-only: the store's immutability contract (objects are sealed;
        # readers must not be able to mutate shared memory)
        return self._view[off.value : off.value + size.value].toreadonly()

    def lookup_pin(self, object_id: str) -> Optional[Tuple[memoryview, int]]:
        """Zero-copy view + PIN: the bytes stay valid across deletes until
        ``unpin(object_id, offset)``.  Returns (view, offset) or None.  The
        ownership/ref-counting contract of the native core (plasma analog:
        reclamation waits for the last reader)."""
        import ctypes

        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.arena_lookup_pin(
            self._h, _key(object_id), ctypes.byref(off), ctypes.byref(size)
        )
        if rc != 1:
            return None
        view = self._view[off.value : off.value + size.value].toreadonly()
        return view, off.value

    def unpin(self, object_id: str, offset: int) -> None:
        """Release one pin.  Safe after close() (no-op on a dead handle)."""
        if self._h >= 0:
            self._lib.arena_unpin(self._h, _key(object_id), offset)

    def pins(self, object_id: str) -> int:
        return int(self._lib.arena_pins(self._h, _key(object_id)))

    def contains(self, object_id: str) -> bool:
        return self.lookup(object_id) is not None

    def delete(self, object_id: str) -> None:
        self._lib.arena_delete(self._h, _key(object_id))

    # -- stats --------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "capacity": int(self._lib.arena_capacity(self._h)),
            "used": int(self._lib.arena_used(self._h)),
            "live_objects": int(self._lib.arena_live_objects(self._h)),
            "sealed_bytes": int(self._lib.arena_sealed_bytes(self._h)),
            "free_bytes": int(self._lib.arena_free_bytes(self._h)),
            "leaked_bytes": int(self._lib.arena_leaked_bytes(self._h)),
        }

    def close(self) -> None:
        """Release the C-side mapping + handle.  The Python mmap backing any
        zero-copy views stays alive via refcounting (views → self._view →
        self._mm), so outstanding reads remain valid."""
        if self._h >= 0:
            self._lib.arena_close(self._h)
            self._h = -1


def open_arena(root: str, create: bool) -> Optional[Arena]:
    """Best-effort arena for a store directory; None when natives are
    unavailable (no compiler) — callers use the file store only."""
    path = os.path.join(root, "__arena__")
    try:
        return Arena(path, create=create)
    except Exception:  # noqa: BLE001 — any native failure degrades to the file store
        return None
