"""Host-side shared-memory object store.

TPU-native replacement for the reference stack's plasma store (SURVEY.md §2B:
"per-node shared-memory store; zero-copy Arrow objects").  Objects are
immutable (Overview_of_Ray.ipynb:cc-4 "Objects. In-memory, immutable"), keyed
by ``ObjectRef``, and shared between the driver and worker processes on the
same host through files in ``/dev/shm`` (tmpfs == shared memory): a writer
serializes with out-of-band buffers (serialization.py), writes to a temp file
and atomically renames to seal; readers ``mmap`` the sealed file and
reconstruct numpy/Arrow payloads zero-copy over the mapping.

A C++ arena-based store (``tpu_air/_native``) provides an accelerated backend
with the same wire format when built; this module is the always-available
fallback and the reference semantics.

Cross-host fetch (DCN) goes through the control plane in ``runtime.py`` —
single-host deployments (everything the reference exercises locally) never hit
it.
"""

from __future__ import annotations

import mmap
import os
import secrets
import time
from typing import Any, Optional

from . import serialization


class ObjectRef:
    """Handle to an immutable object in the store.

    Mirrors the semantics of the reference's ``ray._raylet.ObjectRef`` (leaks
    into user code at Scaling_batch_inference.ipynb:cc-127): hashable, cheap to
    copy between processes, resolvable with ``tpu_air.get``.
    """

    __slots__ = ("id",)

    def __init__(self, id: str):
        self.id = id

    def hex(self) -> str:
        return self.id

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __reduce__(self):
        return (ObjectRef, (self.id,))


def new_object_id() -> str:
    return secrets.token_hex(16)


class ObjectStore:
    """File-per-object store rooted in shared memory.

    The store directory is created by the head process and shared (by path)
    with every worker; any process may put or get.  Sealing is atomic
    (``os.rename``), so a reader either sees a complete object or none.
    """

    def __init__(self, root: str, create: bool = False):
        self.root = root
        if create:
            os.makedirs(root, exist_ok=True)
        # C++ shared-memory arena (plasma analog): preferred home for objects
        # that fit; the file-per-object path remains for large objects,
        # arena-full fallback, and compiler-less environments.
        from . import shm_arena

        self._arena = shm_arena.open_arena(root, create)
        self._arena_retry_at = 0.0

    # -- paths ------------------------------------------------------------
    def _path(self, object_id: str) -> str:
        return os.path.join(self.root, object_id)

    # -- write ------------------------------------------------------------
    def put(self, value: Any, object_id: Optional[str] = None) -> ObjectRef:
        object_id = object_id or new_object_id()
        self.put_serialized(serialization.serialize(value), object_id)
        return ObjectRef(object_id)

    def put_serialized(self, chunks, object_id: str) -> None:
        if self._arena is not None and self._arena.put_chunks(object_id, chunks):
            return
        tmp = self._path(f".tmp-{object_id}-{os.getpid()}")
        with open(tmp, "wb") as f:
            for c in chunks:
                f.write(c)
        os.chmod(tmp, 0o444)  # immutability contract
        os.rename(tmp, self._path(object_id))

    def _maybe_reopen_arena(self) -> None:
        """Heal a failed arena open.  Writers put arena-resident objects with
        no file fallback, so a process whose first open failed (e.g. it raced
        the .so build) must be able to recover — otherwise its gets would
        block forever on objects that only exist in the arena."""
        if self._arena is not None:
            return
        now = time.monotonic()
        if now < self._arena_retry_at:
            return
        self._arena_retry_at = now + 0.5  # rate-limit
        if os.path.exists(os.path.join(self.root, "__arena__")):
            from . import shm_arena

            self._arena = shm_arena.open_arena(self.root, create=False)

    # -- read -------------------------------------------------------------
    def contains(self, object_id: str) -> bool:
        self._maybe_reopen_arena()
        if self._arena is not None and self._arena.contains(object_id):
            return True
        return os.path.exists(self._path(object_id))

    def wait_for(self, object_id: str, timeout: Optional[float] = None) -> bool:
        """Block until the object is sealed. Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0005
        while not self.contains(object_id):
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(delay)
            delay = min(delay * 2, 0.01)
        return True

    def get(self, object_id: str, timeout: Optional[float] = None) -> Any:
        if not self.wait_for(object_id, timeout):
            raise TimeoutError(f"object {object_id} not available after {timeout}s")
        if self._arena is not None:
            view = self._arena.lookup(object_id)
            if view is not None:
                # zero-copy: buffers reference the arena mapping; space is
                # never reused (delete only tombstones), so views stay valid
                return serialization.deserialize(view, zero_copy=True)
        path = self._path(object_id)
        size = os.path.getsize(path)
        if size == 0:
            return serialization.loads(serialization.dumps(None))
        fd = os.open(path, os.O_RDONLY)
        try:
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        # Zero-copy lifetime: out-of-band buffers come back as memoryview
        # slices of the mmap; any numpy array built over them holds a
        # reference chain (ndarray → memoryview → mmap), so the mapping stays
        # valid exactly as long as the value references it.
        return serialization.deserialize(m, zero_copy=True)

    def delete(self, object_id: str) -> None:
        if self._arena is not None:
            self._arena.delete(object_id)
        try:
            os.chmod(self._path(object_id), 0o644)
            os.remove(self._path(object_id))
        except FileNotFoundError:
            pass

    def destroy(self) -> None:
        if self._arena is not None:
            self._arena.close()
            self._arena = None
        try:
            for name in os.listdir(self.root):
                try:
                    os.chmod(os.path.join(self.root, name), 0o644)
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass
            os.rmdir(self.root)
        except OSError:
            pass
