"""Host-side shared-memory object store.

TPU-native replacement for the reference stack's plasma store (SURVEY.md §2B:
"per-node shared-memory store; zero-copy Arrow objects").  Objects are
immutable (Overview_of_Ray.ipynb:cc-4 "Objects. In-memory, immutable"), keyed
by ``ObjectRef``, and shared between the driver and worker processes on the
same host through files in ``/dev/shm`` (tmpfs == shared memory): a writer
serializes with out-of-band buffers (serialization.py), writes to a temp file
and atomically renames to seal; readers ``mmap`` the sealed file and
reconstruct numpy/Arrow payloads zero-copy over the mapping.

A C++ arena-based store (``tpu_air/_native``) provides an accelerated backend
with the same wire format when built; this module is the always-available
fallback and the reference semantics.  The arena owns object lifecycle in
native code (SURVEY.md §2B core_worker row): zero-copy reads hold a
cross-process PIN refcount, ``delete`` parks pinned objects in a zombie
state, and the last unpin reclaims the block into a shared free list for
reuse — the plasma ownership contract.

Cross-host fetch (DCN) goes through the control plane in ``runtime.py`` —
single-host deployments (everything the reference exercises locally) never hit
it.
"""

from __future__ import annotations

import mmap
import os
import secrets
import time
from typing import Any, Optional

from . import serialization
from tpu_air.faults import plan as _faults


class ObjectRef:
    """Handle to an immutable object in the store.

    Mirrors the semantics of the reference's ``ray._raylet.ObjectRef`` (leaks
    into user code at Scaling_batch_inference.ipynb:cc-127): hashable, cheap to
    copy between processes, resolvable with ``tpu_air.get``.
    """

    __slots__ = ("id",)

    def __init__(self, id: str):
        self.id = id

    def hex(self) -> str:
        return self.id

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __reduce__(self):
        return (ObjectRef, (self.id,))


def new_object_id() -> str:
    return secrets.token_hex(16)


class ObjectStore:
    """File-per-object store rooted in shared memory.

    The store directory is created by the head process and shared (by path)
    with every worker; any process may put or get.  Sealing is atomic
    (``os.rename``), so a reader either sees a complete object or none.
    """

    def __init__(self, root: str, create: bool = False):
        self.root = root
        if create:
            os.makedirs(root, exist_ok=True)
        # C++ shared-memory arena (plasma analog): preferred home for objects
        # that fit; the file-per-object path remains for large objects,
        # arena-full fallback, and compiler-less environments.
        from . import shm_arena

        self._arena = shm_arena.open_arena(root, create)
        self._arena_retry_at = 0.0
        # Spilling (reference: "efficient memory usage, object spilling",
        # Introduction_to_Ray_AI_Runtime.ipynb:cc-3): the store root lives in
        # tmpfs (RAM); when file objects exceed TPU_AIR_STORE_BYTES, sealed
        # objects move to a DISK directory and restore transparently on get.
        # 0 (default) = unlimited, no scanning overhead on the hot path.
        self._file_budget = int(os.environ.get("TPU_AIR_STORE_BYTES", "0") or 0)
        # deterministic from root so every process of the session agrees; a
        # user-configured dir gets a per-session subdir so destroy() can
        # never wipe a concurrent session's spilled objects
        session_tag = os.path.basename(root.rstrip(os.sep))
        custom = os.environ.get("TPU_AIR_SPILL_DIR")
        self._spill_dir = (
            os.path.join(custom, session_tag) if custom
            else os.path.join("/var/tmp", f"tpu_air-spill-{session_tag}")
        )

    # -- paths ------------------------------------------------------------
    def _path(self, object_id: str) -> str:
        return os.path.join(self.root, object_id)

    def _spill_path(self, object_id: str) -> str:
        return os.path.join(self._spill_dir, object_id)

    def _ensure_spill_dir(self) -> None:
        """Create the spill dir with an ``.owner`` marker naming the store
        root's absolute path, so the stale-session sweeper can check THAT
        path for liveness instead of guessing at default base dirs."""
        os.makedirs(self._spill_dir, exist_ok=True)
        marker = os.path.join(self._spill_dir, ".owner")
        # written unconditionally: a later session reusing the same spill-dir
        # name (same root basename, different base dir) must not inherit a
        # dead predecessor's marker — the sweeper would reap it as stale
        tmp = f"{marker}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(os.path.abspath(self.root))
                f.flush()
                os.fsync(f.fileno())  # airlint CS002: a torn marker reads
                # as a bogus root path and the sweeper reaps a live session
            os.rename(tmp, marker)
        except OSError:
            pass

    # -- spilling ----------------------------------------------------------
    def _scan_files(self):
        """(mtime, size, name) for sealed file objects under the root."""
        out = []
        try:
            with os.scandir(self.root) as it:
                for e in it:
                    if e.name.startswith((".", "__")):
                        continue  # tmp files / __arena__
                    try:
                        st = e.stat()
                    except OSError:
                        continue
                    out.append((st.st_mtime, st.st_size, e.name))
        except OSError:
            pass
        return out

    def _spill_object(self, name: str) -> bool:
        """Move one sealed object root → spill dir (copy, seal, unlink).
        Concurrent readers stay safe: an already-open mmap survives the
        unlink, and get() falls back to the spill path on FileNotFound."""
        src, dst = self._path(name), self._spill_path(name)
        tmp = os.path.join(self._spill_dir, f".tmp-{name}-{os.getpid()}")
        try:
            import shutil

            shutil.copyfile(src, tmp)
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())  # airlint CS002: copyfile leaves the
                # bytes in page cache; sealing before they are durable can
                # survive a power loss that the data does not — and the
                # source is unlinked right after
            os.chmod(tmp, 0o444)
            os.rename(tmp, dst)  # atomic seal in the spill dir
            os.chmod(src, 0o644)
            os.remove(src)
            return True
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def _make_room(self, need: int) -> bool:
        """Spill oldest sealed objects until ``need`` bytes fit under the
        budget.  True when the new object can be written to the root."""
        if need > self._file_budget:
            # spilling residents can't help — don't evict the hot set for an
            # object that is going to disk regardless
            self._ensure_spill_dir()
            return False
        files = self._scan_files()
        usage = sum(s for _, s, _ in files)
        if usage + need <= self._file_budget:
            return True
        self._ensure_spill_dir()
        for _, size, name in sorted(files):
            if usage + need <= self._file_budget:
                break
            if self._spill_object(name):
                usage -= size
        return usage + need <= self._file_budget

    def spill_stats(self) -> dict:
        objs, total = 0, 0
        try:
            with os.scandir(self._spill_dir) as it:
                for e in it:
                    if e.name.startswith("."):
                        continue
                    try:
                        total += e.stat().st_size
                        objs += 1
                    except OSError:
                        pass
        except OSError:
            pass
        return {"spill_dir": self._spill_dir, "spilled_objects": objs,
                "spilled_bytes": total, "budget_bytes": self._file_budget}

    # -- write ------------------------------------------------------------
    def put(self, value: Any, object_id: Optional[str] = None) -> ObjectRef:
        object_id = object_id or new_object_id()
        if _faults.enabled():
            # the write-side twin of the get hook: every producer (weights
            # publish, batch chunks, journal snapshots) funnels through
            # here, so one site gives the chaos lane a handle on all of
            # them — found by airlint FI001's funnel-coverage audit
            _faults.perturb("object_store.put", key=object_id)
        self.put_serialized(serialization.serialize(value), object_id)
        return ObjectRef(object_id)

    def put_serialized(self, chunks, object_id: str) -> None:
        if self._arena is not None and self._arena.put_chunks(object_id, chunks):
            return
        target_root = self.root
        if self._file_budget:
            need = sum(
                c.nbytes if isinstance(c, memoryview) else len(c) for c in chunks
            )
            if not self._make_room(need):
                # even after spilling everything the new object busts the
                # tmpfs budget — write it straight to disk
                self._ensure_spill_dir()
                target_root = self._spill_dir
        tmp = os.path.join(target_root, f".tmp-{object_id}-{os.getpid()}")
        with open(tmp, "wb") as f:
            for c in chunks:
                f.write(c)
            f.flush()
            os.fsync(f.fileno())  # airlint CS002: the rename seal claims
            # crash atomicity for sealed objects — that claim is only true
            # once the bytes are durable, not just in page cache
        os.chmod(tmp, 0o444)  # immutability contract
        os.rename(tmp, os.path.join(target_root, object_id))

    def _maybe_reopen_arena(self) -> None:
        """Heal a failed arena open.  Writers put arena-resident objects with
        no file fallback, so a process whose first open failed (e.g. it raced
        the .so build) must be able to recover — otherwise its gets would
        block forever on objects that only exist in the arena."""
        if self._arena is not None:
            return
        now = time.monotonic()
        if now < self._arena_retry_at:
            return
        self._arena_retry_at = now + 0.5  # rate-limit
        if os.path.exists(os.path.join(self.root, "__arena__")):
            from . import shm_arena

            self._arena = shm_arena.open_arena(self.root, create=False)

    # -- read -------------------------------------------------------------
    def contains(self, object_id: str) -> bool:
        self._maybe_reopen_arena()
        if self._arena is not None and self._arena.contains(object_id):
            return True
        if os.path.exists(self._path(object_id)):
            return True
        return bool(self._file_budget) and os.path.exists(self._spill_path(object_id))

    def wait_for(self, object_id: str, timeout: Optional[float] = None) -> bool:
        """Block until the object is sealed. Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0005
        while not self.contains(object_id):
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(delay)
            delay = min(delay * 2, 0.01)
        return True

    def get(self, object_id: str, timeout: Optional[float] = None) -> Any:
        if _faults.enabled():
            # "delay" stalls the fetch; "drop" raises the same TimeoutError a
            # real store timeout produces, so recovery paths see the true shape
            _faults.perturb("object_store.get", key=object_id)
        if not self.wait_for(object_id, timeout):
            raise TimeoutError(f"object {object_id} not available after {timeout}s")
        if self._arena is not None:
            pinned = self._arena.lookup_pin(object_id)
            if pinned is not None:
                return self._get_pinned(object_id, *pinned)
        # root first, spill-dir fallback; a concurrent _make_room may move
        # the object between ANY two syscalls here, so both the stat and the
        # open must tolerate disappearance and retry the other location
        fd = size = None
        for _ in range(3):
            for path in (self._path(object_id), self._spill_path(object_id)):
                try:
                    fd = os.open(path, os.O_RDONLY)
                    size = os.fstat(fd).st_size
                    break
                except FileNotFoundError:
                    continue
            if fd is not None:
                break
        if fd is None:
            raise TimeoutError(f"object {object_id} vanished mid-read")
        if size == 0:
            os.close(fd)
            return serialization.loads(serialization.dumps(None))
        try:
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        # Zero-copy lifetime: out-of-band buffers come back as memoryview
        # slices of the mmap; any numpy array built over them holds a
        # reference chain (ndarray → memoryview → mmap), so the mapping stays
        # valid exactly as long as the value references it.
        return serialization.deserialize(m, zero_copy=True)

    def _get_pinned(self, object_id: str, view, offset: int) -> Any:
        """Deserialize an arena object under a read pin (native ownership:
        the C++ arena won't reclaim the bytes while the pin is held).

        The pin is released when the LAST out-of-band buffer holder dies
        (``serialization.deserialize_pinned``), not when the top-level value
        dies: a derived object that escapes its container — a Series pulled
        out of a DataFrame, an array extracted from a dict — keeps its
        holder alive through the buffer-protocol chain, so ``delete`` +
        block reuse can never invalidate memory anything still references.
        A value holding no views (nbuf == 0) unpins immediately.
        """
        import weakref

        try:
            value, holders = serialization.deserialize_pinned(view)
        except BaseException:  # unpin on ANY failure (even KeyboardInterrupt), then surface
            self._arena.unpin(object_id, offset)
            raise
        if not holders:
            self._arena.unpin(object_id, offset)
            return value
        import threading

        # finalizers run in whichever thread drops the last reference, so
        # the countdown must be atomic
        lock = threading.Lock()
        remaining = [len(holders)]
        unpin = self._arena.unpin

        def _release(lock=lock, remaining=remaining, unpin=unpin,
                     object_id=object_id, offset=offset):
            with lock:
                remaining[0] -= 1
                last = remaining[0] == 0
            if last:
                unpin(object_id, offset)

        for h in holders:
            weakref.finalize(h, _release)
        return value

    def delete(self, object_id: str) -> None:
        if self._arena is not None:
            self._arena.delete(object_id)
        for path in (self._path(object_id), self._spill_path(object_id)):
            try:  # chmod best-effort: files are sealed 0o444
                os.chmod(path, 0o644)
            except OSError:
                pass
            try:  # remove regardless — a chmod failure must not skip it
                os.remove(path)
            except FileNotFoundError:
                pass

    def destroy(self) -> None:
        if self._arena is not None:
            self._arena.close()
            self._arena = None
        for d in (self.root, self._spill_dir):
            try:
                for name in os.listdir(d):
                    try:
                        os.chmod(os.path.join(d, name), 0o644)
                        os.remove(os.path.join(d, name))
                    except OSError:
                        pass
                os.rmdir(d)
            except OSError:
                pass
