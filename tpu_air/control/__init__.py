"""tpu_air.control — C++ GCS control plane (SURVEY.md §2B GCS row): cluster
membership, heartbeats/failure detection, actor + object directories, KV."""

from .client import GcsClient, HeartbeatThread, ensure_gcs_binary, start_gcs

__all__ = ["GcsClient", "HeartbeatThread", "ensure_gcs_binary", "start_gcs"]
