"""Python client for the C++ GCS control-plane daemon (_native/gcs_server.cpp).

Framing: 4-byte big-endian length + protobuf (gcs.proto).  One socket per
client, guarded by a lock — control traffic is request/reply and low-rate.
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

_NATIVE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native")

pb = None  # gcs_pb2, resolved lazily (importing this module must not build)


def _ensure_pb():
    """Resolve the generated protobuf bindings, generating them via build.sh
    on first use if absent — lazily, never at module import."""
    global pb
    if pb is None:
        try:
            from . import gcs_pb2 as _pb
        except ImportError:
            subprocess.run(["sh", os.path.join(_NATIVE, "build.sh")],
                           check=True, capture_output=True, timeout=300)
            from . import gcs_pb2 as _pb
        pb = _pb
    return pb


def ensure_gcs_binary() -> str:
    path = os.path.join(_NATIVE, "tpu_air_gcs")
    if not os.path.exists(path):
        subprocess.run(["sh", os.path.join(_NATIVE, "build.sh")],
                       check=True, capture_output=True, timeout=300)
    if not os.path.exists(path):
        raise RuntimeError("tpu_air_gcs failed to build (protobuf dev missing?)")
    return path


def start_gcs(port: int = 0, dead_after_ms: int = 10000,
              timeout: float = 30.0) -> Tuple[subprocess.Popen, int]:
    """Launch the daemon; returns (process, bound_port)."""
    import select

    _ensure_pb()
    proc = subprocess.Popen(
        [ensure_gcs_binary(), str(port), str(dead_after_ms)],
        stdout=subprocess.PIPE, text=True,
    )
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        # select before readline: a daemon wedged pre-printf must not turn
        # the timeout contract into an indefinite block
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(deadline - time.monotonic(), 0.0))
        if not ready:
            break
        line = proc.stdout.readline()
        if line.startswith("LISTENING"):
            return proc, int(line.split()[1])
        if proc.poll() is not None:
            break
    proc.kill()
    raise RuntimeError(f"gcs daemon failed to start: {line!r}")


class GcsClient:
    def __init__(self, address: str):
        _ensure_pb()
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._seq = 0

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, **op) -> pb.Reply:
        with self._lock:
            self._seq += 1
            req = pb.Request(seq=self._seq, **op)
            blob = req.SerializeToString()
            self._sock.sendall(struct.pack(">I", len(blob)) + blob)
            (n,) = struct.unpack(">I", self._recv_exact(4))
            rep = pb.Reply()
            rep.ParseFromString(self._recv_exact(n))
        if not rep.ok:
            raise RuntimeError(f"gcs: {rep.error}")
        return rep

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("gcs connection closed")
            buf += chunk
        return buf

    # -- membership / failure detection -------------------------------------
    def register_node(self, node_id: str, address: str = "", num_chips: int = 0):
        self._call(register_node=pb.NodeInfo(
            node_id=node_id, address=address, num_chips=num_chips))

    def heartbeat(self, node_id: str):
        self._call(heartbeat=node_id)

    def list_nodes(self) -> List[Dict]:
        rep = self._call(list_nodes=True)
        return [
            {"node_id": n.node_id, "address": n.address, "num_chips": n.num_chips,
             "alive": n.alive}
            for n in rep.nodes
        ]

    # -- actor directory -----------------------------------------------------
    def register_actor(self, actor_id: str, node_id: str, name: str = "",
                       chip_ids: Optional[List[int]] = None):
        self._call(register_actor=pb.ActorInfo(
            actor_id=actor_id, name=name, node_id=node_id,
            chip_ids=chip_ids or []))

    def lookup_actor(self, name_or_id: str) -> Optional[Dict]:
        rep = self._call(lookup_actor=name_or_id)
        if not rep.found:
            return None
        a = rep.actor
        return {"actor_id": a.actor_id, "name": a.name, "node_id": a.node_id,
                "chip_ids": list(a.chip_ids), "dead": a.dead}

    def mark_actor_dead(self, actor_id: str):
        self._call(mark_actor_dead=actor_id)

    # -- object directory ----------------------------------------------------
    def publish_object(self, object_id: str, node_id: str, size_bytes: int = 0):
        self._call(publish_object=pb.ObjectLocation(
            object_id=object_id, node_ids=[node_id], size_bytes=size_bytes))

    def locate_object(self, object_id: str) -> Optional[Dict]:
        rep = self._call(locate_object=object_id)
        if not rep.found:
            return None
        return {"object_id": rep.location.object_id,
                "node_ids": list(rep.location.node_ids),
                "size_bytes": rep.location.size_bytes}

    # -- metadata KV ---------------------------------------------------------
    def kv_put(self, key: str, value: bytes):
        self._call(kv_put=pb.KVPut(key=key, value=value))

    def kv_get(self, key: str) -> Optional[bytes]:
        rep = self._call(kv_get=key)
        return rep.value if rep.found else None

    def kv_del(self, key: str):
        self._call(kv_del=key)


class HeartbeatThread(threading.Thread):
    """Periodic node heartbeat (daemon thread; its own client/socket).

    Resilient: a transient RPC failure or a GCS restart must not silently
    stop heartbeats forever — the thread reconnects and re-registers
    ("unknown node" after a daemon restart) until stop() is called."""

    def __init__(self, address: str, node_id: str, interval: float = 1.0,
                 node_address: str = "", num_chips: int = 0):
        super().__init__(daemon=True)
        self.address = address
        self.node_id = node_id
        self.node_address = node_address
        self.num_chips = num_chips
        self.interval = interval
        # NB: must not be named _stop — that shadows Thread._stop and
        # makes threading._after_fork() blow up in forked children
        self._stop_evt = threading.Event()

    def run(self):
        client = None
        while not self._stop_evt.wait(self.interval):
            try:
                if client is None:
                    client = GcsClient(self.address)
                client.heartbeat(self.node_id)
            except RuntimeError:
                # daemon forgot us (restart) — re-register and carry on
                try:
                    client.register_node(self.node_id, self.node_address,
                                         self.num_chips)
                except (ConnectionError, RuntimeError, OSError):
                    pass
            except (ConnectionError, OSError):
                if client is not None:
                    client.close()
                client = None  # reconnect next tick
        if client is not None:
            client.close()

    def stop(self):
        self._stop_evt.set()
