"""CLI: python -m tpu_air.job {submit,status,logs,list,wait} ..."""

from __future__ import annotations

import argparse
import json
import sys

from . import jobs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu_air.job")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("submit", help="submit a job from a YAML spec")
    s.add_argument("spec")
    s.add_argument("--wait", action="store_true")

    for name in ("status", "logs", "wait"):
        sp = sub.add_parser(name)
        sp.add_argument("job_id")

    sub.add_parser("list")

    args = p.parse_args(argv)
    if args.cmd == "submit":
        job_id = jobs.submit(args.spec, wait_for_completion=args.wait)
        st = jobs.get_status(job_id)
        print(json.dumps(st, indent=2, default=str))
        return 0 if st["status"] in ("queued", "running", "succeeded") else 1
    if args.cmd == "status":
        print(json.dumps(jobs.get_status(args.job_id), indent=2, default=str))
        return 0
    if args.cmd == "logs":
        sys.stdout.write(jobs.logs(args.job_id))
        return 0
    if args.cmd == "wait":
        st = jobs.wait(args.job_id)
        print(json.dumps(st, indent=2, default=str))
        return 0 if st["status"] in ("succeeded", "finished") else 1
    if args.cmd == "list":
        print(json.dumps(jobs.list_jobs(), indent=2, default=str))
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
