"""Job runner: spec parsing, submission, status/log retrieval.

Jobs run as detached subprocesses; state lives under
``$TPU_AIR_JOB_ROOT`` (default ``~/.tpu_air/jobs``)/<job_id>/:
  job.json    spec + pid + status (queued/running/succeeded/failed)
  driver.log  combined stdout/stderr of the entrypoint
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


def _job_root() -> str:
    root = os.environ.get(
        "TPU_AIR_JOB_ROOT", os.path.join(os.path.expanduser("~"), ".tpu_air", "jobs")
    )
    os.makedirs(root, exist_ok=True)
    return root


@dataclass
class JobSpec:
    """The YAML schema of the reference job file
    (flan-t5-batch-inference-job-setup.yml:1-7)."""

    name: str
    entrypoint: str
    compute_config: Any = None  # topology name or {num_cpus, num_chips}
    cluster_env: Optional[str] = None  # recorded; env building is out of scope
    working_dir: Optional[str] = None
    env: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_yaml(cls, path: str) -> "JobSpec":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f)
        known = {k: raw[k] for k in
                 ("name", "entrypoint", "compute_config", "cluster_env",
                  "working_dir", "env") if k in raw}
        if "name" not in known or "entrypoint" not in known:
            raise ValueError(f"job spec {path} must define 'name' and 'entrypoint'")
        return cls(**known)


def _job_dir(job_id: str) -> str:
    return os.path.join(_job_root(), job_id)


def _read_state(job_id: str) -> Dict[str, Any]:
    with open(os.path.join(_job_dir(job_id), "job.json")) as f:
        return json.load(f)


def _write_state(job_id: str, state: Dict[str, Any]) -> None:
    path = os.path.join(_job_dir(job_id), "job.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2, default=str)
        f.flush()
        os.fsync(f.fileno())  # found by airlint CS002: the replace below is
        # atomic, but without fsync a power loss could keep the rename and
        # lose the bytes — `air job status` would read a torn job.json
    os.replace(tmp, path)


def _resolve_env(spec: JobSpec) -> Dict[str, str]:
    env = dict(os.environ)
    # the minimal cluster_env: the framework itself must be importable in the
    # job process even when running from a source checkout (the reference's
    # cluster_env ships the full dependency image; here we ship the path)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if pkg_parent not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_parent] + parts)
    cc = spec.compute_config
    if isinstance(cc, dict):
        if "num_chips" in cc:
            env["TPU_AIR_NUM_CHIPS"] = str(cc["num_chips"])
        if "num_cpus" in cc:
            env["TPU_AIR_NUM_CPUS"] = str(cc["num_cpus"])
    env.update({k: str(v) for k, v in (spec.env or {}).items()})
    return env


def submit(spec_or_path, wait_for_completion: bool = False) -> str:
    """Start a job; returns its job_id.  The entrypoint runs detached with
    output teed to driver.log (the `anyscale job submit` analog)."""
    spec = (
        spec_or_path
        if isinstance(spec_or_path, JobSpec)
        else JobSpec.from_yaml(spec_or_path)
    )
    job_id = f"{spec.name}-{int(time.time())}-{os.urandom(3).hex()}"
    jdir = _job_dir(job_id)
    os.makedirs(jdir, exist_ok=True)
    log_path = os.path.join(jdir, "driver.log")

    state = {
        "job_id": job_id,
        "spec": asdict(spec),
        "status": "queued",
        "submitted_at": time.time(),
    }
    _write_state(job_id, state)

    log_f = open(log_path, "wb")  # airlint: disable=CS001 — driver.log is an append-only stream tailed by `air job logs`; readers tolerate a torn tail and there is no atomic-publish contract to seal
    env = _resolve_env(spec)
    env["TPU_AIR_JOB_ID"] = job_id
    proc = subprocess.Popen(
        spec.entrypoint if isinstance(spec.entrypoint, list)
        else shlex.split(spec.entrypoint),
        cwd=spec.working_dir or os.getcwd(),
        env=env,
        stdout=log_f,
        stderr=subprocess.STDOUT,
        start_new_session=True,  # detach: job survives the submitter
    )
    log_f.close()
    state.update(
        status="running",
        pid=proc.pid,
        pid_starttime=_proc_starttime(proc.pid),
        started_at=time.time(),
    )
    _write_state(job_id, state)

    # a tiny watcher keeps job.json's terminal status fresh without the
    # submitter having to stay alive (double-fork-free: daemon thread when
    # waiting, else the status poll in get_status reaps)
    if wait_for_completion:
        rc = proc.wait()
        state.update(
            status="succeeded" if rc == 0 else "failed",
            returncode=rc,
            finished_at=time.time(),
        )
        _write_state(job_id, state)
    return job_id


def _proc_starttime(pid: int) -> Optional[str]:
    """Field 22 (starttime) of /proc/<pid>/stat — a (pid, starttime) pair
    uniquely identifies a process across pid recycling.  Parsed after the
    last ')' so comm values containing spaces/parens can't skew fields."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            tail = f.read().rsplit(")", 1)[1].split()
        return tail[19]  # state is tail[0]; starttime is field 22 overall
    except (OSError, IndexError):
        return None


def _proc_state(pid: int) -> Optional[str]:
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0]
    except (OSError, IndexError):
        return None


def _refresh(state: Dict[str, Any]) -> Dict[str, Any]:
    """Poll liveness of a 'running' job by (pid, starttime) — detached, so no
    waitpid; the starttime marker guards against recycled pids."""
    if state.get("status") != "running":
        return state
    pid = state.get("pid")
    alive = False
    if pid:
        st = _proc_state(pid)
        same_proc = (
            state.get("pid_starttime") is None
            or _proc_starttime(pid) == state.get("pid_starttime")
        )
        alive = st is not None and st not in ("Z", "X") and same_proc
    if not alive:
        # terminal, but the return code is unknown (detached); infer from the
        # log tail — convention: entrypoints print nothing special; mark
        # finished with unknown rc
        state.update(status="finished", finished_at=state.get("finished_at", time.time()))
        _write_state(state["job_id"], state)
    return state


def get_status(job_id: str) -> Dict[str, Any]:
    return _refresh(_read_state(job_id))


def wait(job_id: str, timeout: Optional[float] = None, poll: float = 0.5) -> Dict[str, Any]:
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        st = get_status(job_id)
        if st["status"] not in ("queued", "running"):
            return st
        if deadline is not None and time.monotonic() >= deadline:
            raise TimeoutError(f"job {job_id} still {st['status']} after {timeout}s")
        time.sleep(poll)


def logs(job_id: str) -> str:
    with open(os.path.join(_job_dir(job_id), "driver.log"), "rb") as f:
        return f.read().decode(errors="replace")


def list_jobs() -> List[Dict[str, Any]]:
    out = []
    root = _job_root()
    for name in sorted(os.listdir(root)):
        try:
            out.append(_refresh(_read_state(name)))
        except (FileNotFoundError, json.JSONDecodeError):
            continue
    return out
