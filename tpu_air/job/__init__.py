"""tpu_air.job — headless job submission (the Anyscale-job-CLI analog).

The reference packages W5 as a YAML job spec + CLI submit
(flan-t5-batch-inference-job-setup.yml:1-7: name / compute_config /
cluster_env / entrypoint; `anyscale job submit <yaml>`).  The TPU-native
equivalent runs the entrypoint headless against a local slice: compute_config
becomes the chip/CPU topology the job runtime initializes with.

CLI:  python -m tpu_air.job submit <spec.yml> [--wait]
      python -m tpu_air.job status <job_id>
      python -m tpu_air.job logs <job_id>
      python -m tpu_air.job list
"""

from .jobs import JobSpec, get_status, list_jobs, logs, submit, wait

__all__ = ["JobSpec", "get_status", "list_jobs", "logs", "submit", "wait"]
