#!/usr/bin/env python
"""airlint launcher — works from any cwd without installing the package.

CI gate usage (nonzero exit on any unsuppressed finding)::

    python tools/airlint.py --json tpu_air/
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_air.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
