"""Serving benchmark: paged KV pool vs slab engine vs request-per-call.

The workload is the one the paged pool exists for — a SHARED-PREFIX
arrival trace: N requests drawn over K system prompts (every request is
``system_prompt + private suffix``), with a long+short prompt-length mix.
Three serving paths run the identical staggered schedule:

* **request-per-call** — one B=1 offline ``generate()`` at a time, FIFO;
  arrivals queue behind whole decodes (the no-engine baseline).
* **slab engine** — PR 1 continuous batching (``kv_mode="slab"``): whole
  prompts prefill in one bucketed call, private KV rows, no sharing.
* **paged engine** — block-table pages + prefix cache + chunked prefill:
  repeated system prompts resolve to the SAME physical pages (only the
  private suffix prefills), and long prompts stream in page-sized chunks
  between decode steps instead of stalling them.

Reported: wall/tokens-per-s + client-observed TTFT percentiles per path,
a light-load TTFT-flatness pair (the same short requests with and without
long prompts arriving ahead — chunked prefill should hold their p95 flat),
and the paged pool's prefix hit rate / reused tokens / CoW count for the
trace window.

Greedy decoding everywhere, so all three paths emit identical tokens —
the speedups are schedule/memory effects, not different outputs.

Honest CPU caveat: on CPU each jitted call costs ~2-3 ms of fixed
dispatch+small-compute regardless of size, so the paged engine — which
replaces one bucketed prefill with several page-sized chunk calls — lands
only around parity with the slab engine on wall time here (0.9-1.1x
across runs) even at a >0.8 prefix hit rate.  The layout's wins are HBM-side: slab-equivalent page
count with shared prefixes turning into admission headroom, and bounded
per-step prefill stalls.  On TPU (weight-streaming-bound steps, ~µs
dispatch) the saved prefill FLOPs are the dominant term.

Jit warm-up for every path runs before its timed window, through the SAME
engine instances / generate caches the measurement uses (the paged warm-up
includes one partial-tail CoW so the page-copy program is compiled).
Prints one JSON object; ``--out`` also writes it (the committed
``BENCH_engine.json``).

Run: ``JAX_PLATFORMS=cpu python tools/bench_engine.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _pctl(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(len(xs) * q))]


def _ttft_stats(ttfts, kinds):
    short = [t for t, k in zip(ttfts, kinds) if k == "short"]
    return {
        "ttft_s_mean": round(sum(ttfts) / len(ttfts), 4),
        "ttft_s_p50": round(_pctl(ttfts, 0.50), 4),
        "ttft_s_p95": round(_pctl(ttfts, 0.95), 4),
        "ttft_s_max": round(max(ttfts), 4),
        "ttft_s_p95_short": round(_pctl(short, 0.95), 4),
    }


def _run_engine_trace(engine, schedule, max_new=None):
    """Drive one engine through the arrival schedule; TTFT is measured
    CLIENT-side (submit -> first token on the stream) by a watcher thread
    per request, the latency a streaming caller actually observes."""
    n = len(schedule)
    ttfts = [None] * n
    streams = [None] * n
    watchers = []

    def watch(i, stream, t_submit):
        for _ in stream:  # first token only; result() joins the rest
            ttfts[i] = time.monotonic() - t_submit
            break

    t0 = time.monotonic()
    for i, (arrive, prompt, _kind) in enumerate(schedule):
        now = time.monotonic() - t0
        if now < arrive:
            time.sleep(arrive - now)
        t_submit = time.monotonic()
        streams[i] = engine.submit(prompt, max_new)
        th = threading.Thread(target=watch, args=(i, streams[i], t_submit))
        th.start()
        watchers.append(th)
    tokens = 0
    for s in streams:
        tokens += len(s.result(timeout=600))
    wall = time.monotonic() - t0
    for th in watchers:
        th.join()
    return wall, tokens, ttfts


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--system-prompts", type=int, default=4,
                    help="K distinct shared prefixes the trace draws from")
    ap.add_argument("--max-new", type=int, default=96)
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--slot-len", type=int, default=176)  # 11 pages exactly
    ap.add_argument("--page-len", type=int, default=16)
    ap.add_argument("--prefill-chunks-per-step", type=int, default=4,
                    help="paged prefill quantum (chunk calls per engine step)")
    ap.add_argument("--gap-s", type=float, default=0.02,
                    help="staggered inter-arrival gap")
    ap.add_argument("--tiny", action="store_true",
                    help="LMConfig.tiny smoke run")
    ap.add_argument("--mesh", default=None, metavar="DP,TP",
                    help="also run the trace through a MeshEngine over a "
                         "dp x tp device mesh (needs dp*tp visible devices; "
                         "on CPU: XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=N)")
    ap.add_argument("--disagg", type=int, default=0, metavar="N",
                    help="also run the trace through a DisaggRouter with N "
                         "PrefillWorker actor replicas (initializes the "
                         "tpu_air runtime)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_air.engine import EngineConfig, InferenceEngine
    from tpu_air.models.lm import CausalLM, LMConfig
    from tpu_air.models.lm.generate import generate as lm_generate

    if args.tiny:
        cfg = LMConfig.tiny()
    else:
        cfg = LMConfig(vocab_size=512, d_model=256, n_layers=4, n_heads=8,
                       head_dim=32, d_ff=1024, max_seq_len=512)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), jnp.int32))["params"]

    # -- the trace: K shared system prompts, short/long suffix mix ----------
    # two total lengths only (3C system prefix; +C short / +2C long): the
    # offline baseline compiles two programs, slab prefill two buckets
    C = args.page_len
    sys_len, short_len, long_len = 3 * C, 4 * C, 5 * C
    rng = np.random.RandomState(0)
    sys_prompts = [list(map(int, rng.randint(1, cfg.vocab_size, size=sys_len)))
                   for _ in range(args.system_prompts)]
    schedule = []  # (arrive_s, prompt, kind)
    for i in range(args.requests):
        kind = "long" if i % 4 == 3 else "short"  # 1-in-4 long, interleaved
        total = long_len if kind == "long" else short_len
        suffix = list(map(int, rng.randint(1, cfg.vocab_size,
                                           size=total - sys_len)))
        schedule.append(
            (i * args.gap_s, sys_prompts[i % len(sys_prompts)] + suffix, kind)
        )
    kinds = [k for _, _, k in schedule]

    # eos_token_id=None: every request decodes its full budget on every
    # path, so tokens/s compares equal work (random prompts could otherwise
    # emit EOS at different depths)
    def make_engine(mode, name):
        return InferenceEngine(
            model, params,
            EngineConfig(num_slots=args.num_slots, slot_len=args.slot_len,
                         max_new_tokens=args.max_new, kv_mode=mode,
                         page_len=args.page_len, eos_token_id=None,
                         prefill_chunks_per_step=args.prefill_chunks_per_step),
            name=name,
        )

    slab = make_engine("slab", "engine-bench-slab")
    paged = make_engine("paged", "engine-bench-paged")

    # -- warm-up (excluded): compile every program all paths will run.
    # Engine warms use a token budget of 8: the compiled programs are
    # budget-independent (fixed shapes), so a full-budget warm decode would
    # only burn time.  The offline baseline's scan length IS its budget, so
    # it warms at full max_new.
    for ln in (short_len, long_len):
        warm = list(range(1, ln + 1))
        lm_generate(model, params, [warm], max_new_tokens=args.max_new,
                    eos_token_id=None)
        slab.submit(warm, max_new_tokens=8).result(timeout=600)
        paged.submit(warm, max_new_tokens=8).result(timeout=600)
    # partial-tail re-ask compiles the paged CoW page-copy program
    paged.submit(list(range(1, short_len + 1))[: 3 * C + C // 2],
                 max_new_tokens=8).result(timeout=600)
    slab.metrics.reset_window()
    paged.metrics.reset_window()
    pre = paged.pool.stats()  # cumulative counters: diff out the warm-up

    # -- request-per-call baseline: one B=1 generate at a time, FIFO --------
    t0 = time.monotonic()
    base_lat = []
    for arrive, prompt, _kind in schedule:
        now = time.monotonic() - t0
        if now < arrive:
            time.sleep(arrive - now)
        out = lm_generate(model, params, [prompt],
                          max_new_tokens=args.max_new, eos_token_id=None)
        out.block_until_ready()
        base_lat.append((time.monotonic() - t0) - arrive)
    base_wall = time.monotonic() - t0
    base_tokens = len(schedule) * args.max_new

    # -- slab engine, then paged engine, same schedule ----------------------
    slab_wall, slab_tokens, slab_ttft = _run_engine_trace(slab, schedule)
    slab.close()
    paged_wall, paged_tokens, paged_ttft = _run_engine_trace(paged, schedule)
    post = paged.pool.stats()
    # perf ledger over the trace window (reset_window() cleared the warm-up):
    # analytic flops/bytes per program vs detected peak → roofline fraction,
    # and the useful/wasted token split → goodput ratio
    perf = paged.metrics.snapshot().get("perf", {})
    perf_totals = perf.get("totals", {})
    perf_goodput = perf.get("goodput", {})

    # -- TTFT flatness sub-run (paged, light load): the same shorts with
    # and without long prompts arriving ahead of them.  Slots stay free
    # (no queue wait), so short TTFT isolates PREFILL SCHEDULING — chunked
    # prefill should keep it flat while the longs stream in.  Token streams
    # are disjoint across the two variants (and from the main trace), so
    # prefix hits can't flatter the comparison.
    flat_budget = min(16, args.max_new)
    flat = {}
    for variant in ("short_only", "with_longs"):
        sub = []
        if variant == "with_longs":
            for j in range(2):
                p = list(map(int, rng.randint(1, cfg.vocab_size,
                                              size=long_len)))
                sub.append((j * 0.05, p, "long"))
        for j in range(8):
            p = list(map(int, rng.randint(1, cfg.vocab_size,
                                          size=short_len)))
            sub.append((0.1 + j * 0.05, p, "short"))
        _, _, sub_ttft = _run_engine_trace(paged, sub, max_new=flat_budget)
        shorts = [t for t, (_, _, k) in zip(sub_ttft, sub) if k == "short"]
        flat[variant] = round(_pctl(shorts, 0.95), 4)
    paged.close()

    # -- optional distributed paths (engine/dist/): same schedule ------------
    mesh_block = None
    if args.mesh:
        from tpu_air.engine import MeshEngine

        dp, tp = (int(x) for x in args.mesh.split(","))
        mesh_eng = MeshEngine(
            model, params,
            EngineConfig(num_slots=args.num_slots, slot_len=args.slot_len,
                         max_new_tokens=args.max_new, page_len=args.page_len,
                         eos_token_id=None,
                         prefill_chunks_per_step=args.prefill_chunks_per_step),
            dp=dp, tp=tp, name="engine-bench-mesh")
        for ln in (short_len, long_len):  # compile both prompt shapes
            mesh_eng.submit(list(range(1, ln + 1)),
                            max_new_tokens=8).result(timeout=600)
        mesh_eng.metrics.reset_window()
        mesh_wall, mesh_tokens, mesh_ttft = _run_engine_trace(
            mesh_eng, schedule)
        mesh_block = {
            "mesh": f"{dp}x{tp}",
            "lease": mesh_eng.lease_id,
            "wall_s": round(mesh_wall, 4),
            "tokens_per_s": round(mesh_tokens / mesh_wall, 2),
            **_ttft_stats(mesh_ttft, kinds),
        }
        mesh_eng.close()

    disagg_block = None
    if args.disagg:
        import tpu_air
        from tpu_air.engine import DisaggRouter
        from tpu_air.train import Checkpoint

        tpu_air.init()
        ckpt = Checkpoint.from_model(model_config=cfg, params=params)
        router = DisaggRouter(
            ckpt,
            EngineConfig(num_slots=args.num_slots, slot_len=args.slot_len,
                         max_new_tokens=args.max_new, page_len=args.page_len,
                         eos_token_id=None,
                         prefill_chunks_per_step=args.prefill_chunks_per_step),
            prefill_replicas=args.disagg, name="engine-bench-disagg")
        for ln in (short_len, long_len):  # warm decode + worker prefill jits
            router.submit(list(range(1, ln + 1)), 8).result(timeout=600)
        router.engine.metrics.reset_window()
        dis_wall, dis_tokens, dis_ttft = _run_engine_trace(router, schedule)
        st = router.stats()
        disagg_block = {
            "prefill_replicas": args.disagg,
            "wall_s": round(dis_wall, 4),
            "tokens_per_s": round(dis_tokens / dis_wall, 2),
            **_ttft_stats(dis_ttft, kinds),
            "handoffs": st["handoffs"],
            "fallbacks": st["fallbacks"],
            "kv_bytes_shipped": sum(w.get("bytes_shipped", 0)
                                    for w in st["workers"]),
        }
        router.close()
        tpu_air.shutdown()

    looked = (post["prefix_hits"] - pre["prefix_hits"]) + (
        post["prefix_misses"] - pre["prefix_misses"])
    trace_hits = post["prefix_hits"] - pre["prefix_hits"]
    result = {
        "bench": "engine_paged_kv_shared_prefix_trace",
        "config": {
            "model": ("LMConfig.tiny" if args.tiny
                      else "d256 L4 h8x32 ff1024 v512"),
            "requests": len(schedule),
            "system_prompts": args.system_prompts,
            "prompt_lens": {"short": short_len, "long": long_len,
                            "shared_prefix": sys_len,
                            "long_every": 4},
            "max_new_tokens": args.max_new,
            "num_slots": args.num_slots,
            "slot_len": args.slot_len,
            "page_len": args.page_len,
            "prefill_chunks_per_step": args.prefill_chunks_per_step,
            "arrival": f"staggered, {args.gap_s}s gap",
            "platform": jax.devices()[0].platform,
            "mesh": args.mesh or None,
            "disagg_prefill_replicas": args.disagg or 0,
        },
        "request_per_call": {
            "wall_s": round(base_wall, 4),
            "tokens_per_s": round(base_tokens / base_wall, 2),
            # the baseline cannot stream: its "first token" only becomes
            # visible when the whole call returns (time to first RESPONSE)
            "ttfr_s_mean": round(sum(base_lat) / len(base_lat), 4),
            "ttfr_s_p95": round(_pctl(base_lat, 0.95), 4),
            "ttfr_s_max": round(max(base_lat), 4),
        },
        "slab_engine": {
            "wall_s": round(slab_wall, 4),
            "tokens_per_s": round(slab_tokens / slab_wall, 2),
            **_ttft_stats(slab_ttft, kinds),
        },
        "paged_engine": {
            "wall_s": round(paged_wall, 4),
            "tokens_per_s": round(paged_tokens / paged_wall, 2),
            **_ttft_stats(paged_ttft, kinds),
            "prefix_hit_rate": round(trace_hits / looked, 3) if looked else 0.0,
            "prefix_tokens_reused": (post["prefix_tokens_reused"]
                                     - pre["prefix_tokens_reused"]),
            "cow_copies": post["cow_copies"] - pre["cow_copies"],
            "pages_total": post["pages_total"],
            "roofline_fraction": round(
                perf_totals.get("roofline_fraction", 0.0), 6),
            "model_flops_per_s": round(perf_totals.get("flops_per_s", 0.0), 1),
            "goodput_ratio": round(perf_goodput.get("goodput_ratio", 0.0), 4),
            "peak_source": (perf.get("peak") or {}).get("source"),
        },
        "speedup_paged_vs_request_per_call": round(base_wall / paged_wall, 3),
        "speedup_paged_vs_slab": round(slab_wall / paged_wall, 3),
        # light-load paged runs: short-request TTFT p95 with vs without
        # long prompts arriving ahead — ~1.0 means chunked prefill kept
        # short TTFT flat while the longs streamed in page-sized pieces
        "short_ttft_p95_flatness": {
            "short_only_s": flat["short_only"],
            "with_longs_s": flat["with_longs"],
            "ratio": round(flat["with_longs"]
                           / max(flat["short_only"], 1e-9), 3),
        },
    }
    if mesh_block is not None:
        result["mesh_engine"] = mesh_block
    if disagg_block is not None:
        result["disagg"] = disagg_block
    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
