"""Continuous-batching vs request-per-call serving benchmark.

The engine's reason to exist is throughput under CONCURRENT load: a
request-per-call server runs one B=1 ``generate()`` at a time, so arrivals
queue behind whole decodes; the engine admits them into free slots of the
SAME pool step, so each step's weight streaming is amortized across every
in-flight request.  This bench measures both paths under an identical
staggered arrival schedule and reports tokens/s + time-to-first-token.

Model dials: big enough that a decode step is weight-streaming-bound (the
regime where batching pays — per-step cost grows sublinearly in rows), yet
CPU-runnable in ~a minute.  ``--tiny`` drops to LMConfig.tiny for a quick
smoke run (expect batching NOT to win there: at toy scale the baseline's
fused whole-decode scan has near-zero per-token dispatch cost while the
engine pays a Python host visit per step — the honest tradeoff).

Jit warm-up for BOTH paths runs before the timed window, through the SAME
engine instance / compiled programs the measurement uses.  Prints one JSON
object; ``--out`` also writes it (the committed ``BENCH_engine.json``).

Run: ``JAX_PLATFORMS=cpu python tools/bench_engine.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _make_requests(seed, n, lens, vocab):
    import numpy as np

    rng = np.random.RandomState(seed)
    return [
        list(map(int, rng.randint(1, vocab, size=rng.choice(lens))))
        for _ in range(n)
    ]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=48)
    ap.add_argument("--num-slots", type=int, default=8)
    ap.add_argument("--slot-len", type=int, default=64)
    ap.add_argument("--gap-s", type=float, default=0.02,
                    help="staggered inter-arrival gap")
    ap.add_argument("--tiny", action="store_true",
                    help="LMConfig.tiny smoke run")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tpu_air.engine import EngineConfig, InferenceEngine
    from tpu_air.models.lm import CausalLM, LMConfig
    from tpu_air.models.lm.generate import generate as lm_generate

    if args.tiny:
        cfg = LMConfig.tiny()
    else:
        cfg = LMConfig(vocab_size=512, d_model=256, n_layers=4, n_heads=8,
                       head_dim=32, d_ff=1024, max_seq_len=512)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    # two prompt shapes only: bounds baseline jit compiles to two programs
    # (offline generate compiles per (B, L)), and both land on engine
    # prefill buckets exactly
    lens = [8, 16]
    prompts = _make_requests(0, args.requests, lens, cfg.vocab_size)
    arrivals = [i * args.gap_s for i in range(len(prompts))]

    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=args.num_slots, slot_len=args.slot_len,
                     max_new_tokens=args.max_new),
        name="engine-bench",
    )

    # -- warm-up (excluded): compile every program both paths will run,
    # through the SAME engine/generate caches the timed windows use
    for ln in lens:
        warm = list(range(1, ln + 1))
        lm_generate(model, params, [warm], max_new_tokens=args.max_new)
        engine.submit(warm).result(timeout=600)
    engine.metrics.reset_window()

    # -- request-per-call baseline: one B=1 generate at a time, FIFO --------
    t_start = time.monotonic()
    base_lat = []
    for arrive, p in zip(arrivals, prompts):
        now = time.monotonic() - t_start
        if now < arrive:
            time.sleep(arrive - now)
        out = lm_generate(model, params, [p], max_new_tokens=args.max_new)
        out.block_until_ready()
        base_lat.append((time.monotonic() - t_start) - arrive)
    base_wall = time.monotonic() - t_start
    base_tokens = len(prompts) * args.max_new

    # -- engine: same schedule, requests share slot-pool steps --------------
    t_start = time.monotonic()
    streams = []
    for arrive, p in zip(arrivals, prompts):
        now = time.monotonic() - t_start
        if now < arrive:
            time.sleep(arrive - now)
        streams.append(engine.submit(p))
    for s in streams:
        s.result(timeout=600)
    eng_wall = time.monotonic() - t_start
    eng_tokens = sum(len(s.tokens_so_far()) for s in streams)
    snap = engine.metrics.snapshot()
    engine.close()

    result = {
        "bench": "engine_continuous_batching_vs_request_per_call",
        "config": {
            "model": ("LMConfig.tiny" if args.tiny
                      else "d256 L4 h8x32 ff1024 v512"),
            "requests": len(prompts),
            "prompt_lens": lens,
            "max_new_tokens": args.max_new,
            "num_slots": args.num_slots,
            "slot_len": args.slot_len,
            "arrival": f"staggered, {args.gap_s}s gap",
            "platform": jax.devices()[0].platform,
        },
        "request_per_call": {
            "wall_s": round(base_wall, 4),
            "tokens_per_s": round(base_tokens / base_wall, 2),
            # the baseline cannot stream: its "first token" only becomes
            # visible when the whole call returns (time to first RESPONSE)
            "ttfr_s_mean": round(statistics.mean(base_lat), 4),
            "ttfr_s_max": round(max(base_lat), 4),
        },
        "engine": {
            "wall_s": round(eng_wall, 4),
            "tokens_per_s": round(eng_tokens / eng_wall, 2),
            "ttft_s_mean": round(snap["ttft_s"]["mean"], 4),
            "ttft_s_max": round(snap["ttft_s"]["max"], 4),
            "step_latency_s_p50": round(snap["step_latency_s"]["p50"], 4),
        },
        "engine_speedup_tokens_per_s": round(base_wall / eng_wall, 3),
    }
    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
