"""Open-loop serve-plane benchmark: SLO-aware admission under Poisson load.

Unlike ``bench_engine.py`` (closed-loop, driver-embedded engines), this
bench exercises the REAL serving path end to end: HTTP proxy → SLO
admission (priority class + token budget) → least-loaded replica actor →
streaming submit/poll with replica pinning — all under airtrace spans.

The workload is OPEN-LOOP: arrivals follow seeded Poisson processes whose
rates do not slow down when the system backs up (the honest way to measure
overload behaviour — a closed loop self-throttles and hides queueing
collapse).  Each arrival is a streaming client thread: one
``{"action": "submit"}`` POST (TTFT clock starts), then pinned
``{"action": "poll"}`` POSTs until ``done``.

Two phases run against the same deployment, and the INTERACTIVE arrival
rate is IDENTICAL in both — only the background (batch + best_effort)
rate changes.  That isolates the SLO claim: background pressure, not
interactive self-load, is what must not move interactive latency.

* **underload** — background arrivals well inside capacity; every class
  admits.  Interactive TTFT here is the baseline.
* **overload** — background arrivals far past capacity; the admission
  controller queues then sheds best_effort and batch (503 + Retry-After)
  while ``reserved_interactive_slots`` keeps decode slots available to
  interactive, whose p99 TTFT must hold ~flat vs the underload baseline
  (the ``interactive_p99_ratio`` headline; tests/test_serve_slo.py
  asserts ≤1.2x with a CPU-noise floor).

A third **swap** phase measures the live weight hot-swap path
(tpu_air/serve/weights.py): underload-rate traffic runs while a
WeightsController publishes + canary-promotes the SAME weights across
the fleet mid-phase.  Headlines: ``swap_stall_ms`` — the worst decode
gap any replica's swap introduced (fleet-merged
``tpu_air_weights_swap_stall_ms_max``) — and ``swap_errors_total``,
which must stay 0 (a swap drops no streams).

A fourth **preemption** phase measures lease-revocation recovery
(docs/RESILIENCE.md "Preemption & migration"): two single-chip replicas
serve underload-rate traffic while a seeded ``runtime.lease`` notice
revokes one replica's chip mid-phase; the PreemptionWatcher drains it
and live-migrates its KV pages to the survivor.  Headlines:
``preemption_recovery_ms`` — worst notice-to-out-of-rotation
orchestration wall time — and ``migrated_vs_replayed`` — the fraction
of rescued streams that moved with their KV state (zero re-prefill)
rather than falling back to journal replay; with a generous notice it
must be 1.0.

A fifth **batch** phase measures the elastic offline lane
(tpu_air/batch, docs/SERVING.md "Batch lane"): a ``BatchJob`` epoch
streams rows through the route at ``best_effort`` while the interactive
trace runs open-loop — first a trough (base interactive rate; the job
borrows the idle chip via ``scale_up`` and widens its window), then a
spike (6x interactive rate, longer streams; depth crosses
``borrow_depth_high`` and the loan is preempted back through the
lease-notice drain).  The phase gets a FRESH runtime and watch: the job
bills the cost ledger as tenant ``batch:<job_id>``, which would dilute
the main run's pinned ``cost.tenants.default.token_share = 1.0``.
Headline: ``rows_s_per_chip`` — epoch rows per ledger-accounted engine
chip-second (attributed + idle), so holding a borrowed chip without
converting it to rows costs the number.

Reported per phase and class: arrivals, completed, shed (proxy 503s and
engine-side overload look identical to the client), proxy-side
queued/shed counter deltas, TTFT p50/p99 both CLIENT-observed (includes
bench-harness noise — hundreds of client threads share this process's
GIL) and ENGINE-recorded (submit → first token inside the serving plane;
the headline ratio reads this one), plus phase tokens/s.

The whole run executes with airwatch installed (observability/watch.py):
the driver-side FleetScraper rides along exactly as it would in
production, and its per-tenant cost ledger yields the ``cost`` section —
``chip_seconds_per_1k_tokens`` (attributed busy chip-seconds per 1k
tokens, the $/token proxy) and the per-tenant token split.  Bench
traffic carries no ``adapter_id``, so every token must land on the
``default`` tenant (``cost.tenants.default.token_share`` pins 1.0).

Honest CPU caveat: on XLA:CPU a decode step costs ~2-3 ms dispatch, so
absolute TTFTs here are noise-dominated; what transfers to TPU is the
SHAPE — shed ordering (best_effort first, interactive never) and the
interactive TTFT ratio between the two phases.

Prints one JSON object; ``--out`` also writes it (the committed
``BENCH_serve.json``).  Run: ``JAX_PLATFORMS=cpu python tools/bench_serve.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PORT = 8219
#: background arrivals split batch / best_effort
BACKGROUND_MIX = (("batch", 0.6), ("best_effort", 0.4))


def _pctl(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(len(xs) * q))]


def _post(path, payload, headers=None, timeout=60.0):
    """POST JSON; returns (status, body_dict, response_headers)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{PORT}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


class _Client:
    """One open-loop arrival: streaming submit + pinned polls to done,
    over ONE persistent HTTP/1.1 connection (the proxy is thread-per-
    connection — keep-alive means one proxy thread per client for its
    whole stream instead of one per poll).

    Interactive clients poll tight (latency is their SLO); background
    clients poll lazily — which also keeps a backlog of batch streams from
    saturating the replica's serial message loop with poll RPCs and
    queueing interactive traffic behind them."""

    def __init__(self, prompt, priority, max_new):
        self.prompt = prompt
        self.priority = priority
        self.max_new = max_new
        self.poll_s = 0.005 if priority == "interactive" else 0.08
        self.outcome = None       # "ok" | "shed" | "error"
        self.ttft_s = None        # submit sent -> first token observed
        self.tokens = 0
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _post(self, payload, headers=None):
        """POST on the persistent connection; reopens once on a stale
        keep-alive socket.  Returns (status, body_dict, resp_headers)."""
        import http.client

        body = json.dumps(payload).encode()
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        for attempt in (0, 1):
            if self._conn is None:
                import socket

                self._conn = http.client.HTTPConnection(
                    "127.0.0.1", PORT, timeout=60.0)
                self._conn.connect()
                # Nagle off: tiny pipelined polls must not wait out the
                # server's delayed ACK on the reused socket
                self._conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                self._conn.request("POST", "/engine", body=body,
                                   headers=hdrs)
                resp = self._conn.getresponse()
                data = json.loads(resp.read())
                return resp.status, data, dict(resp.headers)
            except Exception:  # noqa: BLE001 — stale keep-alive socket: reopen once
                try:
                    self._conn.close()
                finally:
                    self._conn = None
                if attempt:
                    raise
        raise RuntimeError("unreachable")

    def _run(self):
        self._conn = None
        t0 = time.monotonic()
        try:
            try:
                status, out, hdrs = self._post({
                    "action": "submit", "prompt": self.prompt,
                    "max_new_tokens": self.max_new,
                    "priority": self.priority,
                })
            except Exception:  # noqa: BLE001 — transport failure = client error
                self.outcome = "error"
                return
            if status == 503:
                self.outcome = "shed"
                return
            if status != 200:
                self.outcome = "error"
                return
            rid = out["request_id"]
            pin = {"x-tpu-air-replica": hdrs.get("x-tpu-air-replica", "")}
            cursor = 0
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                try:
                    status, out, _ = self._post({
                        "action": "poll", "request_id": rid,
                        "cursor": cursor,
                    }, headers=pin)
                except Exception:  # noqa: BLE001 — transient poll failure: retry
                    time.sleep(0.01)
                    continue
                if status != 200:
                    self.outcome = "error"
                    return
                got = out.get("tokens") or []
                if got and self.ttft_s is None:
                    self.ttft_s = time.monotonic() - t0
                cursor += len(got)
                if out.get("done"):
                    self.tokens = cursor
                    self.outcome = "ok"
                    return
                time.sleep(self.poll_s)
            self.outcome = "error"  # poll deadline
        finally:
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:  # noqa: BLE001 — socket teardown is best-effort
                    pass


def _scrape_admission():
    """The proxy's cumulative per-class admission counters."""
    try:
        status, stats, _ = _post("/-/stats", {})
    except Exception:  # noqa: BLE001 — stats scrape is best-effort
        return {}
    if status != 200 or "/engine" not in stats:
        return {}
    adm = stats["/engine"]["admission"]
    return {k: dict(adm.get(k) or {}) for k in ("admitted", "queued", "shed")}


def _counter_delta(after, before):
    return {
        k: {p: after.get(k, {}).get(p, 0) - before.get(k, {}).get(p, 0)
            for p in after.get(k, {})}
        for k in after
    }


def _run_phase(interactive_rps, background_rps, duration_s, prompts,
               max_new, rng):
    """One open-loop phase: merged Poisson arrivals (interactive at a
    FIXED rate + background at the phase's rate) for ``duration_s``."""
    before = _scrape_admission()
    clients = []
    total_rate = interactive_rps + background_rps
    t_start = time.monotonic()
    t_end = t_start + duration_s
    i = 0
    while time.monotonic() < t_end:
        # merged process: this arrival is interactive with probability
        # rate_i / rate_total, else a background class from the fixed mix
        if rng.random() < interactive_rps / total_rate:
            priority = "interactive"
        else:
            r, acc = rng.random(), 0.0
            priority = BACKGROUND_MIX[-1][0]
            for klass, share in BACKGROUND_MIX:
                acc += share
                if r < acc:
                    priority = klass
                    break
        c = _Client(prompts[i % len(prompts)], priority, max_new)
        clients.append(c)
        c.thread.start()
        i += 1
        # open loop: the NEXT arrival time does not depend on service
        time.sleep(rng.expovariate(total_rate))
    for c in clients:
        c.thread.join(timeout=180.0)
    wall = time.monotonic() - t_start

    # engine-recorded per-class TTFT (submit -> first token INSIDE the
    # serving plane): free of bench-harness noise — a few hundred client
    # threads sharing this process's GIL put tens-of-ms outliers into the
    # client-observed tail that no server ever saw.  The deployment is
    # fresh per phase, so the gauge window holds only this phase's samples.
    engine_ttft = {}
    from tpu_air.engine.metrics import merge_snapshots
    from tpu_air.serve.proxy import replica_engine_stats

    replica_snaps = replica_engine_stats()
    # fleet-merged view: per-class TTFT quantiles from the MERGED histogram
    # buckets (mergeable across replicas — not a max-of-p99s), and the perf
    # ledger's roofline/goodput totals summed over replicas
    fleet = merge_snapshots(replica_snaps) if replica_snaps else {}
    for klass, pr in (fleet.get("priority") or {}).items():
        d = pr.get("ttft_s") or {}
        if d.get("count"):
            engine_ttft[klass] = {"p50": d["p50"], "p99": d["p99"],
                                  "count": d["count"]}
    perf = fleet.get("perf") or {}

    by_class = {}
    for klass in ("interactive", "batch", "best_effort"):
        mine = [c for c in clients if c.priority == klass]
        ttfts = [c.ttft_s for c in mine if c.ttft_s is not None]
        by_class[klass] = {
            "arrivals": len(mine),
            "completed": sum(1 for c in mine if c.outcome == "ok"),
            "shed": sum(1 for c in mine if c.outcome == "shed"),
            "errors": sum(1 for c in mine if c.outcome == "error"),
            "client_ttft_s_p50": round(_pctl(ttfts, 0.50), 4),
            "client_ttft_s_p99": round(_pctl(ttfts, 0.99), 4),
            "engine_ttft_s": engine_ttft.get(klass),
        }
    total_tokens = sum(c.tokens for c in clients)
    return {
        "interactive_rps": interactive_rps,
        "background_rps": background_rps,
        "arrivals": len(clients),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 2) if wall else 0.0,
        "roofline_fraction": round(
            (perf.get("totals") or {}).get("roofline_fraction", 0.0), 6),
        "goodput_ratio": round(
            (perf.get("goodput") or {}).get("goodput_ratio", 0.0), 4),
        "classes": by_class,
        "proxy_counters_delta": _counter_delta(_scrape_admission(), before),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds per rate phase")
    ap.add_argument("--interactive-rps", type=float, default=4.0,
                    help="interactive arrival rate, SAME in both phases")
    ap.add_argument("--underload-rps", type=float, default=2.5,
                    help="background (batch+best_effort) rate, underload")
    ap.add_argument("--overload-rps", type=float, default=70.0,
                    help="background rate, overload")
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import tpu_air
    from tpu_air import serve
    from tpu_air.engine import EngineConfig
    from tpu_air.models.lm import CausalLM, LMConfig
    from tpu_air.observability import tracing
    from tpu_air.observability import watch as watch_mod
    from tpu_air.serve import AdmissionPolicy, EngineDeployment
    from tpu_air.train import Checkpoint

    cfg = LMConfig.tiny()
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    ckpt = Checkpoint.from_model(model_config=cfg, params=params)

    rng = random.Random(args.seed)
    np_rng = np.random.RandomState(args.seed)
    prompts = [list(map(int, np_rng.randint(1, 384, size=np_rng.randint(4, 12))))
               for _ in range(16)]

    engine_cfg = EngineConfig(
        num_slots=4, slot_len=64, max_new_tokens=args.max_new, max_queue=16,
        reserved_interactive_slots=2,
    )
    # thresholds sized to the tiny engine: best_effort queues at 2 queued
    # per replica and sheds at 6; batch queues at 6, sheds at 12
    policy = AdmissionPolicy(queue_soft=2.0, queue_high=6.0, queue_hard=12.0)

    tpu_air.init(num_cpus=4, num_chips=8)
    tracing.enable()
    # airwatch rides along for the whole run: serve.run starts the
    # FleetScraper against each phase's deployment, and the cost ledger
    # accumulates per-tenant attribution across phases (counter resets at
    # phase boundaries re-baseline without attributing negative deltas)
    fleet_watch = watch_mod.install(watch_mod.WatchConfig(
        interval_s=0.5, seed=args.seed))
    result = {
        "bench": "serve_slo_open_loop",
        "config": {
            "model": "LMConfig.tiny",
            "phase_duration_s": args.duration,
            "interactive_rps": args.interactive_rps,
            "background_mix": {k: v for k, v in BACKGROUND_MIX},
            "max_new_tokens": args.max_new,
            "num_slots": engine_cfg.num_slots,
            "reserved_interactive_slots":
                engine_cfg.reserved_interactive_slots,
            "max_queue": engine_cfg.max_queue,
            "admission": {"queue_soft": policy.queue_soft,
                          "queue_high": policy.queue_high,
                          "queue_hard": policy.queue_hard},
            "platform": jax.default_backend(),
        },
    }
    try:
        for name, bg_rate in (("underload", args.underload_rps),
                              ("overload", args.overload_rps)):
            # fresh deployment per phase: the engine's rolling TTFT gauge
            # window then holds exactly this phase's samples (serve.run on
            # the same route retires the previous replicas)
            serve.run(
                EngineDeployment.options(
                    name="bench-engine", route_prefix="/engine"
                ).bind(ckpt, engine_cfg),
                port=PORT,
                admission_policy=policy,
            )
            # warm-up: compile the prefill/decode programs OUTSIDE the
            # timed window (one full blocking generate through the proxy;
            # the XLA cache makes the second phase's warm-up instant).
            # Tagged batch so its compile-inclusive TTFT sample stays OUT
            # of the interactive gauge the headline ratio reads.
            _post("/engine", {"prompt": prompts[0], "priority": "batch",
                              "max_new_tokens": args.max_new}, timeout=300.0)
            result[name] = _run_phase(args.interactive_rps, bg_rate,
                                      args.duration, prompts, args.max_new,
                                      rng)

        # -- swap phase: live hot-swap under streaming load ---------------
        import tempfile

        from tpu_air.engine.metrics import merge_snapshots
        from tpu_air.serve import WeightsController, WeightStore
        from tpu_air.serve.proxy import replica_engine_stats
        from tpu_air.serve.weights import compute_probe

        h = serve.run(
            EngineDeployment.options(
                name="bench-engine", route_prefix="/engine"
            ).bind(ckpt, engine_cfg),
            port=PORT,
            admission_policy=policy,
        )
        _post("/engine", {"prompt": prompts[0], "priority": "batch",
                          "max_new_tokens": args.max_new}, timeout=300.0)
        store = WeightStore(tempfile.mkdtemp(prefix="bench-wstore-"))
        store.publish(
            params, metadata={"bench": True},
            probe=compute_probe(model, params, prompts[:2], max_new=4))
        ctl = WeightsController(h, store.root, probe_prompts=prompts[:2],
                                probe_max_new=4, soak_s=0.3)
        promote_out = {}

        def _promote():
            # fire mid-phase so the swap lands under live decode traffic
            time.sleep(args.duration / 3.0)
            promote_out.update(ctl.promote())

        th = threading.Thread(target=_promote, daemon=True)
        th.start()
        result["swap"] = _run_phase(args.interactive_rps,
                                    args.underload_rps, args.duration,
                                    prompts, args.max_new, rng)
        th.join(timeout=120.0)
        merged_w = (merge_snapshots(replica_engine_stats())
                    if replica_engine_stats() else {}).get("weights") or {}
        result["swap"]["promote"] = promote_out
        result["swap_stall_ms"] = round(
            float(merged_w.get("max_stall_ms", 0.0)), 3)
        result["swap_errors_total"] = sum(
            c["errors"] for c in result["swap"]["classes"].values())

        # -- preemption phase: lease-notice revocation under live load ----
        from tpu_air import faults
        from tpu_air.faults import FaultPlan, FaultSpec
        from tpu_air.serve.proxy import serve_control_stats

        # fresh runtime: earlier phases rotated the chip pool, and the
        # fault spec targets the replica whose lease key is "chips=1" —
        # a clean pool makes the two replicas land on chips 0 and 1
        serve.shutdown()
        tpu_air.shutdown()
        tpu_air.init(num_cpus=4, num_chips=8)
        # delay_s counts from the replica's lease ATTACH (deploy time).
        # Warmup compiles BOTH replicas in parallel (below) and costs a
        # few seconds of fresh-process XLA compile, so a full duration of
        # delay lands the notice a few seconds INTO the arrival window —
        # while the doomed replica has streams decoding (live KV to
        # migrate).  delay_s = duration/2 used to race the compile: a slow
        # warmup let the notice fire before any traffic, and the phase
        # measured a drain of nothing (migrations=0, recovery ~1ms).
        plan = FaultPlan(seed=args.seed, specs=[
            FaultSpec("runtime.lease", "notice", at=1, match="chips=1",
                      delay_s=args.duration, notice_s=60.0)])
        # max_restarts=0: this phase measures the DRAIN + MIGRATE cost, not
        # replacement-spawn cost — and a respawn would re-lease the revoked
        # chip (lowest free id) in a fresh process whose per-process fault
        # counter re-fires the seeded notice, turning the phase into a
        # preemption loop.  Long streams (max_new 320, slot_len 336): on
        # CPU a decode step costs ~2-3 ms, so a 12-token stream lives
        # ~40 ms and even an 80-token one ~0.25 s — at these arrival
        # rates the notice instant would catch a live slot on the doomed
        # replica only by luck.  ~320-token streams live ~1 s, which
        # keeps expected occupancy ≥1 slot per replica so the drain has
        # live KV state to move.  Half background rate: the survivor must
        # stay shallow-queued after capacity halves — queued
        # (not-yet-decoding) streams can only be rescued by replay, and a
        # deep post-kill queue admission-sheds best_effort replays,
        # polluting the migrate-vs-replay signal.
        preempt_max_new = max(args.max_new, 320)
        preempt_cfg = EngineConfig(
            num_slots=engine_cfg.num_slots, slot_len=336,
            max_new_tokens=preempt_max_new, max_queue=engine_cfg.max_queue,
            reserved_interactive_slots=engine_cfg.reserved_interactive_slots,
        )
        serve.run(
            EngineDeployment.options(
                name="bench-engine", route_prefix="/engine",
                num_replicas=2, num_chips=1, max_restarts=0,
            ).bind(ckpt, preempt_cfg),
            port=PORT,
            admission_policy=policy,
            fault_plan=plan,
        )
        # warm up BOTH replicas in parallel (replicas are separate worker
        # processes — each compiles its own prefill/decode programs for
        # the preempt shapes).  The handle round-robins idle replicas and
        # counts its own in-flight calls, so two concurrent blocking
        # generates land on different replicas; serially they would
        # compile back-to-back and push the phase past the lease notice.
        warm_threads = [
            threading.Thread(
                target=_post,
                args=("/engine", {"prompt": prompts[0], "priority": "batch",
                                  "max_new_tokens": preempt_max_new}),
                kwargs={"timeout": 300.0}, daemon=True)
            for _ in range(2)]
        t_warm = time.monotonic()
        warm_threads[0].start()
        time.sleep(0.2)
        warm_threads[1].start()
        for th_w in warm_threads:
            th_w.join(timeout=300.0)
        warmup_s = round(time.monotonic() - t_warm, 3)
        result["preemption"] = _run_phase(args.interactive_rps,
                                          args.underload_rps / 2.0,
                                          args.duration,
                                          prompts, preempt_max_new, rng)
        rec = serve_control_stats().get("recovery") or {}
        # warmup wall vs the notice delay: the notice fires delay_s after
        # lease attach, so (delay_s - warmup_s) is how far INTO the
        # arrival window it landed — diagnostic for a run where the drain
        # caught nothing live
        result["preemption"]["warmup_s"] = warmup_s
        result["preemption"]["recovery"] = {
            k: rec.get(k) for k in (
                "preemptions", "migrations", "migrated_pages",
                "migration_fallbacks", "replays", "replay_failures",
                "preemption_recovery_ms")}
        result["preemption_recovery_ms"] = round(
            float(rec.get("preemption_recovery_ms") or 0.0), 3)
        rescued = int(rec.get("migrations") or 0) + int(rec.get("replays") or 0)
        result["migrated_vs_replayed"] = round(
            int(rec.get("migrations") or 0) / rescued, 3) if rescued else 0.0
        result["preemption_errors_total"] = sum(
            c["errors"] for c in result["preemption"]["classes"].values())

        under = result["underload"]["classes"]["interactive"]
        over = result["overload"]["classes"]["interactive"]
        # the headline: engine-recorded interactive p99 TTFT under
        # background overload vs the underload baseline (CPU noise floor
        # keeps a 3ms-vs-1ms blip from reading as 3x); the client-observed
        # ratio rides along for the harness-inclusive view
        floor = 0.05
        u99 = (under.get("engine_ttft_s") or {}).get(
            "p99", under["client_ttft_s_p99"])
        o99 = (over.get("engine_ttft_s") or {}).get(
            "p99", over["client_ttft_s_p99"])
        result["interactive_p99_ratio"] = round(
            max(o99, floor) / max(u99, floor), 3)
        result["interactive_client_p99_ratio"] = round(
            max(over["client_ttft_s_p99"], floor)
            / max(under["client_ttft_s_p99"], floor), 3)
        result["overload_shed_total"] = sum(
            c["shed"] for c in result["overload"]["classes"].values())
        result["interactive_shed_total"] = (
            result["underload"]["classes"]["interactive"]["shed"]
            + over["shed"])

        # -- airwatch cost attribution over the whole run -----------------
        # one final synchronous scrape closes the last attribution
        # interval, then the ledger's fleet headline becomes the bench's
        # $/token proxy: attributed busy chip-seconds per 1k tokens
        fleet_watch.scrape_once()
        led = fleet_watch.ledger.snapshot()
        head = led.get("headline") or {}
        result["cost"] = {
            "chip_seconds_per_1k_tokens": round(
                float(head.get("chip_seconds_per_1k_tokens", 0.0)), 4),
            "chip_seconds_attributed": round(
                float(head.get("chip_seconds_attributed", 0.0)), 3),
            "idle_chip_seconds": round(
                float(led.get("idle_chip_seconds", 0.0)), 3),
            "tokens_total": round(float(head.get("tokens_total", 0.0)), 1),
            "intervals": int(led.get("intervals", 0)),
            "watch_scrapes": int(fleet_watch.scrapes),
            "watch_anomalies": len(fleet_watch.events(kind="watch.anomaly")),
            "tenants": {
                name: {
                    "tokens_total": round(
                        float(t.get("tokens_total", 0.0)), 1),
                    "token_share": round(float(t.get("token_share", 0.0)), 4),
                    "chip_seconds": round(
                        float(t.get("chip_seconds", 0.0)), 3),
                    "chip_seconds_per_1k_tokens": round(
                        float(t.get("chip_seconds_per_1k_tokens", 0.0)), 4),
                }
                for name, t in (led.get("tenants") or {}).items()
            },
        }

        # -- batch phase: offline epoch with borrowing, trough + spike ----
        from tpu_air.batch import BatchJob, BatchJobConfig
        from tpu_air.data import from_items

        # fresh runtime AND a fresh watch: the job bills the ledger as
        # tenant batch:<job_id>, which would dilute the pinned
        # cost.tenants.default.token_share = 1.0 headline above — the
        # lane gets its own ledger and a clean chip pool
        serve.shutdown()
        tpu_air.shutdown()
        watch_mod.clear()
        tpu_air.init(num_cpus=4, num_chips=8)
        batch_watch = watch_mod.install(watch_mod.WatchConfig(
            interval_s=0.5, seed=args.seed))
        serve.run(
            EngineDeployment.options(
                name="bench-engine", route_prefix="/engine",
                num_replicas=1, num_chips=1,
            ).bind(ckpt, engine_cfg),
            port=PORT,
            admission_policy=policy,
        )
        # warm the replica's prefill buckets across the prompt-length
        # range — a fresh process recompiles per bucket, and a multi-
        # second compile stall under the spike reads as interactive shed
        for wp in (prompts[0], min(prompts, key=len), max(prompts, key=len)):
            _post("/engine", {"prompt": wp, "priority": "batch",
                              "max_new_tokens": args.max_new}, timeout=300.0)

        n_rows = max(48, int(round(args.duration * 25)))
        ds = from_items([{"prompt": prompts[i % len(prompts)]}
                         for i in range(n_rows)], parallelism=4)
        # thresholds sized to the tiny engine: the job's own queued rows
        # sit ~2 deep (window 4, two non-reserved slots), under the
        # borrow gate in the trough; the spike's longer interactive
        # streams queue past borrow_depth_high and preempt the loan back
        job = BatchJob(ds, job_id="bench-epoch", config=BatchJobConfig(
            route_prefix="/engine", max_new_tokens=args.max_new,
            priority="best_effort", num_shards=2, seed=args.seed,
            chunk_rows=8, window=4, borrow=True,
            borrow_depth_low=2.5, borrow_depth_high=3.0,
            borrow_notice_s=5.0))
        job_out = {}

        def _epoch():
            job_out.update(job.run())

        jth = threading.Thread(target=_epoch, daemon=True)
        t_batch = time.monotonic()
        jth.start()
        result["batch_trough"] = _run_phase(
            args.interactive_rps, 0.0, args.duration / 2.0,
            prompts, args.max_new, rng)
        result["batch_spike"] = _run_phase(
            args.interactive_rps * 6.0, 0.0, args.duration / 2.0,
            prompts, max(args.max_new, 32), rng)
        jth.join(timeout=600.0)
        batch_wall = round(time.monotonic() - t_batch, 3)

        # one synchronous scrape closes the last attribution interval;
        # the denominator is TOTAL engine chip-time the lane's ledger saw
        # (attributed + idle) — the borrowed replica counts only while
        # the loan is held
        batch_watch.scrape_once()
        bled = batch_watch.ledger.snapshot()
        bhead = bled.get("headline") or {}
        chip_s = (float(bhead.get("chip_seconds_attributed", 0.0))
                  + float(bled.get("idle_chip_seconds", 0.0)))
        if chip_s <= 0.0:
            chip_s = batch_wall  # ledger empty (scraper raced shutdown)
        rows_done = int(job_out.get("rows_done") or 0)
        result["batch"] = {
            "wall_s_epoch": batch_wall,
            "chip_seconds": round(chip_s, 3),
            "job": {k: job_out.get(k) for k in (
                "state", "rows_total", "rows_done", "rows_per_s",
                "chunks_done", "checkpoints", "borrows", "borrow_returns",
                "borrowed_replicas", "shed_retries", "submit_retries")},
            "cost": {
                "batch_chip_seconds": round(
                    float(bhead.get("batch_chip_seconds", 0.0)), 3),
                "interactive_chip_seconds": round(
                    float(bhead.get("interactive_chip_seconds", 0.0)), 3),
                "batch_chip_share": round(
                    float(bhead.get("batch_chip_share", 0.0)), 4),
            },
        }
        result["rows_s_per_chip"] = round(rows_done / chip_s, 3) \
            if chip_s else 0.0
        result["batch_errors_total"] = sum(
            c["errors"]
            for ph in ("batch_trough", "batch_spike")
            for c in result[ph]["classes"].values())
    finally:
        serve.shutdown()
        tpu_air.shutdown()
        watch_mod.clear()
        from tpu_air import faults as _faults

        _faults.clear()

    blob = json.dumps(result, indent=1)
    print(blob)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main()
