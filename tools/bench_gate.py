"""Bench regression gate: committed artifacts vs committed baseline.

Compares the HEADLINE fields of the committed bench artifacts
(``BENCH_engine.json``, ``BENCH_serve.json``) against
``BENCH_BASELINE.json`` and exits non-zero when any field regressed past
its threshold.  Tier-1 runs it (tests/test_bench_gate.py), so a PR that
commits a regressed artifact — or forgets to commit one — fails CI
loudly instead of silently shifting the baseline.

The baseline file declares what "headline" means, per artifact:

    {
      "threshold": 0.2,
      "benches": {
        "BENCH_serve.json": {
          "overload.tokens_per_s": {"value": 500.0, "direction": "higher"},
          "interactive_p99_ratio": {"value": 1.0, "direction": "lower"},
          "overload.classes.interactive.shed": {"value": 0,
                                                 "direction": "lower"}
        }
      }
    }

* keys are dotted paths into the artifact JSON;
* ``direction: "higher"`` fails when current < baseline * (1 - threshold);
* ``direction: "lower"`` fails when current > baseline * (1 + threshold)
  (a zero baseline makes ANY increase a failure — how the gate pins
  "interactive is never shed");
* a per-field ``"threshold"`` overrides the file-level default (0.2).

Missing artifacts, missing fields, or unparsable JSON are FAILURES, not
skips — the gate's job is to notice exactly that.

Run: ``python tools/bench_gate.py`` (from anywhere; paths resolve
against the repo root).  ``--threshold`` overrides the file default;
positional args override which artifacts are checked.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASELINE = "BENCH_BASELINE.json"


def _lookup(obj, dotted):
    """Resolve ``a.b.c`` into nested dicts; raises KeyError on any miss."""
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(dotted)
        cur = cur[part]
    if not isinstance(cur, (int, float)) or isinstance(cur, bool):
        raise KeyError(f"{dotted} is not numeric")
    return float(cur)


def check(baseline: dict, root: str, only=None, threshold=None):
    """Returns (failures, report_lines); failures == [] means gate passes."""
    failures, lines = [], []
    default_thr = float(threshold if threshold is not None
                        else baseline.get("threshold", 0.2))
    benches = baseline.get("benches")
    if not isinstance(benches, dict) or not benches:
        return (["baseline has no 'benches' section"], lines)
    for artifact, fields in benches.items():
        if only and artifact not in only:
            continue
        path = os.path.join(root, artifact)
        try:
            with open(path) as f:
                current = json.load(f)
        except (OSError, ValueError) as e:
            failures.append(f"{artifact}: unreadable ({e})")
            continue
        for dotted, spec in fields.items():
            try:
                base_val = float(spec["value"])
                direction = spec["direction"]
            except (KeyError, TypeError, ValueError):
                failures.append(
                    f"{artifact}:{dotted}: malformed baseline spec {spec!r}")
                continue
            if direction not in ("higher", "lower"):
                failures.append(
                    f"{artifact}:{dotted}: bad direction {direction!r}")
                continue
            thr = float(spec.get("threshold", default_thr))
            try:
                cur_val = _lookup(current, dotted)
            except KeyError as e:
                failures.append(f"{artifact}:{dotted}: missing field ({e})")
                continue
            if direction == "higher":
                limit = base_val * (1.0 - thr)
                ok = cur_val >= limit
                want = f">= {limit:.4g}"
            else:
                limit = base_val * (1.0 + thr)
                ok = cur_val <= limit
                want = f"<= {limit:.4g}"
            tag = "ok  " if ok else "FAIL"
            lines.append(
                f"{tag} {artifact}:{dotted} = {cur_val:.4g} "
                f"(baseline {base_val:.4g}, {direction}-is-better, "
                f"want {want})")
            if not ok:
                failures.append(
                    f"{artifact}:{dotted} regressed: {cur_val:.4g} vs "
                    f"baseline {base_val:.4g} (limit {want})")
    return failures, lines


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="*",
                    help="artifact filenames to check (default: all in the "
                         "baseline)")
    ap.add_argument("--baseline", default=os.path.join(REPO, BASELINE))
    ap.add_argument("--root", default=REPO,
                    help="directory the artifact paths resolve against")
    ap.add_argument("--threshold", type=float, default=None,
                    help="override the baseline's default threshold")
    args = ap.parse_args(argv)

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_gate: FAIL baseline unreadable: {e}")
        return 1

    failures, lines = check(baseline, args.root,
                            only=set(args.artifacts) or None,
                            threshold=args.threshold)
    for line in lines:
        print(f"bench_gate: {line}")
    if failures:
        for f in failures:
            print(f"bench_gate: FAIL {f}")
        print(f"bench_gate: {len(failures)} failure(s)")
        return 1
    print("bench_gate: all headline fields within threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
