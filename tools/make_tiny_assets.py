"""Generate the vendored tiny real-format assets (tests/assets/flan_t5_tiny).

Everything is produced OFFLINE from in-repo material:

* ``spiece.model`` — a REAL unigram sentencepiece model (wire format)
  TRAINED by the in-repo EM trainer (models/sentencepiece_unigram.py
  train_unigram) on this repository's own documentation as the corpus;
* ``tokenizer.json`` — the same vocabulary exported through the Rust
  ``tokenizers`` library (the HF fast-tokenizer format), used as the
  cross-implementation Viterbi parity oracle;
* ``config.json`` + ``model.safetensors`` — a tiny REAL HF T5 checkpoint
  written by ``transformers`` itself (deterministic seed), exercising the
  true ``load_t5_from_hf`` import path;
* ``asset_meta.json`` — expectations the asset-tier tests read (min vocab,
  probe words, min params), so the same tests scale up to the genuine
  flan-t5-small assets when those are present.

Rerun with:  python tools/make_tiny_assets.py
"""

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "tests", "assets", "flan_t5_tiny")

VOCAB = 1024
EXTRA_IDS = 16


def corpus():
    texts = []
    for pattern in ("docs/*.md", "README.md", "SURVEY.md"):
        for p in sorted(glob.glob(os.path.join(REPO, pattern))):
            with open(p) as f:
                texts.append(f.read())
    return texts


def main():
    os.makedirs(OUT, exist_ok=True)
    from tpu_air.models.sentencepiece_unigram import train_t5_tokenizer

    tok = train_t5_tokenizer(corpus(), vocab_size=VOCAB, extra_ids=EXTRA_IDS)
    tok.save_pretrained(OUT)
    print(f"spiece.model: {tok.vocab_size} ids "
          f"({os.path.getsize(os.path.join(OUT, 'spiece.model'))} bytes)")

    # Rust-format export: the parity oracle file
    from tokenizers import Tokenizer, models, pre_tokenizers

    sp = tok.sp
    vocab = [(p, s) for p, s, _ in sp.pieces]
    vocab += [(f"<extra_id_{i}>", 0.0)
              for i in reversed(range(EXTRA_IDS))]  # HF order: id_15 first
    rust = Tokenizer(models.Unigram(vocab, unk_id=sp.unk_id,
                                    byte_fallback=False))
    rust.pre_tokenizer = pre_tokenizers.Metaspace(
        replacement="▁", prepend_scheme="first", split=False
    )
    rust.save(os.path.join(OUT, "tokenizer.json"))
    print("tokenizer.json written")

    # tiny real HF T5 checkpoint (transformers' own save path)
    import torch
    import transformers

    torch.manual_seed(0)
    cfg = transformers.T5Config(
        vocab_size=tok.vocab_size,
        d_model=64, d_kv=16, d_ff=128,
        num_layers=2, num_decoder_layers=2, num_heads=4,
        relative_attention_num_buckets=8,
        feed_forward_proj="gated-gelu",
        tie_word_embeddings=False,
        pad_token_id=0, eos_token_id=1, decoder_start_token_id=0,
    )
    model = transformers.T5ForConditionalGeneration(cfg)
    model.save_pretrained(OUT)
    n = sum(p.numel() for p in model.parameters())
    print(f"checkpoint written: {n} params")

    meta = {
        "min_vocab": tok.vocab_size,
        "min_params": int(n),
        # words guaranteed segmentable+round-trippable (they appear in the
        # training corpus)
        "probe_text": "the framework trains the model over the device mesh",
        "probe_words": ["framework", "model", "mesh"],
    }
    with open(os.path.join(OUT, "asset_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    print("asset_meta.json written")


if __name__ == "__main__":
    main()
