"""Short-sequence flash-attention tile sweep (VERDICT r3 next-round #6).

The Pallas kernel loses to XLA dense at seq 512 (0.87x, BASELINE.md kernel
table) with the auto tiles; this sweeps (block_q, block_k) candidates at
short sequence lengths on the real chip and prints a table, so the
crossover either moves down or the 512-einsum default is confirmed with
data.  Slope-timed (two scan lengths; fixed sync costs cancel — see
bench.py's module docstring for why single timings lie under the tunnel).

Run ON the chip (single process — never concurrently with bench.py):
    python tools/tune_flash_tiles.py [--seq 512] [--bh 48] [--d 64]
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def slope_time(fn, q, k, v, steps=512, reps=3):
    # steps must be large enough that 2*steps of attention dwarf the
    # ~66 ms tunnel round-trip, or the 25%-slope validity gate NaNs out
    # (r5: steps=8 at seq 512 was ~3 ms of compute against 66 ms of RTT)
    import jax
    import jax.numpy as jnp

    def chain(n):
        def body(c, _):
            o = fn(c, k, v)
            return o, ()

        def run(q, k, v):
            out, _ = jax.lax.scan(body, q, None, length=n)
            return jnp.sum(out)

        return jax.jit(run)

    short, long_ = chain(steps), chain(3 * steps)
    float(short(q, k, v))
    float(long_(q, k, v))
    ts, tl = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); float(short(q, k, v)); ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); float(long_(q, k, v)); tl.append(time.perf_counter() - t0)
    ms, ml = sorted(ts)[reps // 2], sorted(tl)[reps // 2]
    per = (ml - ms) / (2 * steps)
    return per if ml - ms > 0.25 * ml else float("nan")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--bh", type=int, default=48)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--steps", type=int, default=512)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tpu_air.ops.flash_attention import flash_attention

    L, BH, D = args.seq, args.bh, args.d
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (BH, L, D), jnp.bfloat16)
    k = jax.random.normal(rng, (BH, L, D), jnp.bfloat16)
    v = jax.random.normal(rng, (BH, L, D), jnp.bfloat16)
    flops = 4.0 * BH * L * L * D  # qk + pv matmuls

    def dense(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32))
        p = jax.nn.softmax(s * (1.0 / D**0.5), axis=-1)
        return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)

    per = slope_time(dense, q, k, v, steps=args.steps)
    print(f"dense: {per*1e3:8.3f} ms  {flops/per/1e12:6.1f} TF/s")

    candidates = [b for b in (64, 128, 256, 512) if L % b == 0]
    results = []
    for bq in candidates:
        for bk in candidates:
            def fn(q, k, v, bq=bq, bk=bk):
                return flash_attention(q, k, v, block_q=bq, block_k=bk)

            try:
                per = slope_time(fn, q, k, v, steps=args.steps)
            except Exception as e:  # noqa: BLE001
                print(f"flash bq={bq:4d} bk={bk:4d}: FAILED {type(e).__name__}")
                continue
            tf = flops / per / 1e12 if per == per and per > 0 else float("nan")
            results.append((tf, bq, bk, per))
            print(f"flash bq={bq:4d} bk={bk:4d}: {per*1e3:8.3f} ms  {tf:6.1f} TF/s")
    if results:
        best = max(results)
        print(f"\nbest flash: bq={best[1]} bk={best[2]} at {best[0]:.1f} TF/s "
              f"(seq {L}); update flash_min_seq_len / auto tiles if it beats "
              "dense")


if __name__ == "__main__":
    main()
