#!/usr/bin/env python
"""watch_dump — query the airwatch plane (observability/watch.py) off a
running dashboard, or the local process's installed Watch.

Usage::

    # fleet summary: scrape counters, anomalies, tenant cost headline
    python tools/watch_dump.py --url http://127.0.0.1:8265

    # per-tenant cost ledger only
    python tools/watch_dump.py --url http://127.0.0.1:8265 --tenants

    # recent watch.anomaly / note events (with trace exemplars)
    python tools/watch_dump.py --url http://127.0.0.1:8265 --events

    # one metric's time series from a downsampling tier
    python tools/watch_dump.py --metric fleet.tokens_per_s --step 10

    # machine-readable: the raw JSON payloads instead of the text report
    python tools/watch_dump.py --json

    # no dashboard: read THIS process's installed Watch (scripts that
    # import tpu_air, install airwatch, run work, then exec this file)
    python tools/watch_dump.py --local

See docs/OBSERVABILITY.md ("airwatch") for the data model.
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fetch(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _local_payloads(metric=None, step=None):
    from tpu_air.observability import watch as watch_mod

    w = watch_mod.current()
    if w is None:
        return {"enabled": False}, {"enabled": False, "tenants": {}}, []
    series = (w.store.series(metric, step=step)
              if metric and metric in w.store.metrics() else [])
    return w.payload(), {"enabled": True, **w.ledger.snapshot()}, series


def render_events(events, out=sys.stdout) -> None:
    w = out.write
    if not events:
        w("no events recorded\n")
        return
    for e in events:
        kind = e.get("event", "?")
        if kind == "watch.anomaly":
            w(f"[{e.get('ts', 0):.1f}] ANOMALY {e['metric']}: "
              f"value={e.get('value', 0):.4g} mean={e.get('mean', 0):.4g} "
              f"z={e.get('zscore', 0):.2f} (threshold {e.get('threshold', 0):.2f}, "
              f"window {e.get('window_s', 0):g}s)")
            if e.get("trace_exemplar"):
                w(f"  trace={e['trace_exemplar']}")
            w("\n")
        else:
            attrs = {k: v for k, v in e.items() if k not in ("event", "ts")}
            w(f"[{e.get('ts', 0):.1f}] {kind}: "
              + " ".join(f"{k}={v}" for k, v in sorted(attrs.items())) + "\n")


def render_tenants(ledger, out=sys.stdout) -> None:
    w = out.write
    tenants = ledger.get("tenants") or {}
    if not tenants:
        w("no tenant activity attributed yet\n")
        return
    head = ledger.get("headline") or {}
    w(f"{'tenant':<20} {'tokens':>10} {'share':>7} {'chip_s':>10} "
      f"{'cs/1k tok':>10} {'kv_page_s':>10} {'sheds':>6} {'quota':>6}\n")
    for name, t in sorted(tenants.items(),
                          key=lambda kv: -kv[1].get("tokens_total", 0)):
        w(f"{name:<20} {t.get('tokens_total', 0):>10.0f} "
          f"{t.get('token_share', 0):>7.2%} "
          f"{t.get('chip_seconds', 0):>10.2f} "
          f"{t.get('chip_seconds_per_1k_tokens', 0):>10.3f} "
          f"{t.get('kv_page_seconds', 0):>10.1f} "
          f"{t.get('sheds', 0):>6.0f} {t.get('quota_rejected', 0):>6.0f}\n")
    w(f"\nheadline: {head.get('tokens_total', 0):.0f} tokens, "
      f"{head.get('chip_seconds_attributed', 0):.2f} attributed chip-s "
      f"({ledger.get('idle_chip_seconds', 0):.2f} idle) -> "
      f"{head.get('chip_seconds_per_1k_tokens', 0):.3f} chip-s per 1k tokens"
      f" over {ledger.get('intervals', 0)} intervals\n")


def render_summary(payload, ledger, out=sys.stdout) -> None:
    w = out.write
    if not payload.get("enabled"):
        w("airwatch is not installed on the target "
          "(call observability.watch.install())\n")
        return
    cfg = payload.get("config") or {}
    store = payload.get("store") or {}
    w(f"airwatch: {payload.get('scrapes', 0)} scrapes @ "
      f"{cfg.get('interval_s', 0):g}s, seed={cfg.get('seed')}, "
      f"ttl={cfg.get('ttl_s', 0):g}s\n")
    w(f"store: {store.get('metrics', 0)} metrics, "
      f"{store.get('samples_recorded', 0)} samples, "
      f"{store.get('buckets_resident', 0)} buckets over tiers "
      f"{store.get('tiers')}\n")
    w(f"anomalies: {payload.get('anomalies', 0)} total\n")
    det = payload.get("detector") or {}
    for metric, st in sorted(det.items()):
        w(f"  {metric:<28} mean={st.get('mean', 0):>10.4g} "
          f"dev={st.get('deviation', 0):>9.4g} n={st.get('samples', 0):>5} "
          f"z*={st.get('threshold', 0):.2f}\n")
    anomalies = [e for e in payload.get("events") or []
                 if e.get("event") == "watch.anomaly"]
    if anomalies:
        w(f"\nrecent anomalies ({len(anomalies)}):\n")
        render_events(anomalies[-10:], out)
    w("\n")
    render_tenants(ledger, out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="http://127.0.0.1:8265",
                    help="dashboard base URL (default %(default)s)")
    ap.add_argument("--local", action="store_true",
                    help="read this process's Watch, no dashboard needed")
    ap.add_argument("--tenants", action="store_true",
                    help="print only the per-tenant cost ledger")
    ap.add_argument("--events", action="store_true",
                    help="print only the recent event ring")
    ap.add_argument("--metric", default=None,
                    help="print one metric's series (e.g. fleet.tokens_per_s)")
    ap.add_argument("--step", type=float, default=None,
                    help="tier step in seconds for --metric (default finest)")
    ap.add_argument("--json", action="store_true",
                    help="emit raw JSON instead of the text report")
    args = ap.parse_args(argv)

    if args.local:
        payload, ledger, series = _local_payloads(args.metric, args.step)
    else:
        base = args.url.rstrip("/")
        payload = _fetch(f"{base}/api/watch")
        ledger = _fetch(f"{base}/api/tenants")
        series = []
        if args.metric:
            # the dashboard serves series through /api/watch's store stats
            # only; remote per-metric series need --local on the serving
            # process (the store is driver-side state, not exported raw)
            print("--metric requires --local (the raw rings live in the "
                  "serving process)", file=sys.stderr)
            return 2

    if args.metric and args.local:
        if args.json:
            print(json.dumps(series, indent=2))
        else:
            for b in series:
                print(f"ts={b['ts']:<12g} count={b['count']:<5} "
                      f"last={b['last']:.6g} mean={b['mean']:.6g} "
                      f"min={b['min']:.6g} max={b['max']:.6g}")
        return 0
    if args.json:
        doc = {"watch": payload, "tenants": ledger}
        print(json.dumps(doc, indent=2))
        return 0
    if args.tenants:
        render_tenants(ledger)
        return 0
    if args.events:
        render_events(payload.get("events") or [])
        return 0
    render_summary(payload, ledger)
    return 0


if __name__ == "__main__":
    sys.exit(main())
