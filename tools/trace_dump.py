#!/usr/bin/env python
"""trace_dump — pull airtrace spans off a running dashboard (or the local
recorder) and write chrome://tracing-loadable JSON.

Usage::

    # list recent traces on a live dashboard
    python tools/trace_dump.py --url http://127.0.0.1:8265 --list

    # export everything (or one trace) to a file for chrome://tracing /
    # ui.perfetto.dev
    python tools/trace_dump.py --url http://127.0.0.1:8265 -o trace.json
    python tools/trace_dump.py --url http://127.0.0.1:8265 \
        --trace-id 0af7651916cd43dd8448eb211c80319c -o one_request.json

    # no dashboard: dump THIS process's recorder (mostly for scripts that
    # import tpu_air, enable tracing, run work, then exec this file)
    python tools/trace_dump.py --local -o trace.json

See docs/OBSERVABILITY.md for the export workflow.
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fetch(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="http://127.0.0.1:8265",
                    help="dashboard base URL (default %(default)s)")
    ap.add_argument("--trace-id", default=None,
                    help="export only this trace (32-hex id)")
    ap.add_argument("--list", action="store_true",
                    help="print recent trace summaries instead of exporting")
    ap.add_argument("--local", action="store_true",
                    help="dump this process's recorder, no dashboard needed")
    ap.add_argument("-o", "--output", default="trace.json",
                    help="output file for the chrome-trace JSON")
    args = ap.parse_args(argv)

    if args.local:
        from tpu_air.observability import trace_export, tracing

        if args.list:
            for t in tracing.trace_summaries():
                print(f"{t['trace_id']}  {t['root']:<30} "
                      f"{t['spans']:>4} spans  {t['duration_ms']:.2f} ms")
            return 0
        n = trace_export.export_file(args.output, trace_id=args.trace_id)
        print(f"wrote {n} spans to {args.output}")
        return 0

    base = args.url.rstrip("/")
    if args.list:
        payload = _fetch(f"{base}/api/traces")
        if not payload.get("enabled"):
            print("tracing is disabled on the target "
                  "(set TPU_AIR_TRACE=1 or call tracing.enable())",
                  file=sys.stderr)
        for t in payload.get("traces", []):
            print(f"{t['trace_id']}  {t['root']:<30} "
                  f"{t['spans']:>4} spans  {t['duration_ms']:.2f} ms"
                  + (f"  [{t['errors']} errors]" if t.get("errors") else ""))
        return 0

    url = f"{base}/api/traces/export"
    if args.trace_id:
        url += f"?trace_id={args.trace_id}"
    doc = _fetch(url)
    with open(args.output, "w") as f:
        json.dump(doc, f)
    n = doc.get("otherData", {}).get("spans", 0)
    print(f"wrote {n} spans to {args.output} "
          "(load in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
