#!/usr/bin/env python
"""trace_dump — pull airtrace spans off a running dashboard (or the local
recorder) and write chrome://tracing-loadable JSON.

Usage::

    # list recent traces on a live dashboard
    python tools/trace_dump.py --url http://127.0.0.1:8265 --list

    # export everything (or one trace) to a file for chrome://tracing /
    # ui.perfetto.dev
    python tools/trace_dump.py --url http://127.0.0.1:8265 -o trace.json
    python tools/trace_dump.py --url http://127.0.0.1:8265 \
        --trace-id 0af7651916cd43dd8448eb211c80319c -o one_request.json

    # no dashboard: dump THIS process's recorder (mostly for scripts that
    # import tpu_air, enable tracing, run work, then exec this file)
    python tools/trace_dump.py --local -o trace.json

    # render a flight-recorder postmortem (written on worker death when
    # TPU_AIR_POSTMORTEM_DIR is set) as a human-readable report
    python tools/trace_dump.py --postmortem /var/crash/postmortem-...json

See docs/OBSERVABILITY.md for the export workflow.
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fetch(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def render_postmortem(data: dict, out=sys.stdout) -> None:
    """Human-readable report from one postmortem JSON (schema
    tpu-air-postmortem/1 — observability/postmortem.py)."""
    w = out.write
    ctx = data.get("context") or {}
    w(f"postmortem: {data.get('reason')}\n")
    w(f"  captured at unix_time={data.get('unix_time')}\n")
    if ctx:
        w(f"  worker={ctx.get('worker_id')} pid={ctx.get('pid')} "
          f"actor={ctx.get('actor_id')} busy_task={ctx.get('busy_task')}\n")
        if ctx.get("outstanding_tasks"):
            w(f"  outstanding tasks ({len(ctx['outstanding_tasks'])}):\n")
            for t in ctx["outstanding_tasks"]:
                w(f"    - {t}\n")
    cluster = data.get("cluster") or {}
    if cluster.get("initialized"):
        res = cluster.get("resources", {})
        w(f"\ncluster: {len(cluster.get('workers', {}))} workers, "
          f"{len(cluster.get('actors', {}))} actors, "
          f"queue_depth={cluster.get('queue_depth')}, "
          f"cpus={res.get('cpu')} chips={res.get('chip')}\n")
        for aid, a in (cluster.get("actors") or {}).items():
            flag = " DEAD" if a.get("dead") else ""
            w(f"  actor {aid} ({a.get('name') or 'anon'}) "
              f"worker={a.get('worker_id')} pending={a.get('pending')}{flag}\n")
    engines = data.get("engines") or {}
    for name, s in engines.items():
        if not isinstance(s, dict):
            continue
        perf = s.get("perf") or {}
        totals = perf.get("totals") or {}
        goodput = perf.get("goodput") or {}
        w(f"\nengine {name}: tokens={s.get('tokens_generated')} "
          f"retired={s.get('requests_retired')} "
          f"queue={s.get('queue_depth')}\n")
        if totals:
            w(f"  roofline_fraction={totals.get('roofline_fraction', 0):.3f} "
              f"flops/s={totals.get('flops_per_s', 0):.3e}\n")
        if goodput:
            w(f"  goodput_ratio={goodput.get('goodput_ratio', 0):.3f} "
              f"(useful={goodput.get('useful', 0)} "
              f"wasted={goodput.get('wasted', 0)})\n")
    slo = data.get("slo")
    if isinstance(slo, dict) and slo.get("slos"):
        burning = set(slo.get("burning") or [])
        w("\nslo state:\n")
        for s in slo["slos"]:
            mark = " BURNING" if s["name"] in burning else ""
            rates = " ".join(
                f"{int(win['window_s'])}s={win['burn_rate']:.2f}x"
                for win in s.get("windows", []))
            w(f"  {s['name']} (obj={s['objective']}): {rates}{mark}\n")
    traces = data.get("traces") or {}
    spans = traces.get("spans") or {}
    for tid, span_list in spans.items():
        w(f"\ntrace {tid} ({len(span_list)} spans):\n")
        by_id = {s["span_id"]: s for s in span_list}
        roots = [s for s in span_list
                 if not s.get("parent_id") or s["parent_id"] not in by_id]
        kids: dict = {}
        for s in span_list:
            kids.setdefault(s.get("parent_id"), []).append(s)

        def _walk(span, depth):
            dur_ms = (span["end_ns"] - span["start_ns"]) / 1e6
            err = (f"  [{span['status']}]"
                   if str(span.get("status", "ok")).startswith("error") else "")
            w(f"  {'  ' * depth}{span['name']}  {dur_ms:.2f} ms{err}\n")
            for c in sorted(kids.get(span["span_id"], []),
                            key=lambda x: x["start_ns"]):
                _walk(c, depth + 1)

        for r in sorted(roots, key=lambda x: x["start_ns"]):
            _walk(r, 1)
    recent = traces.get("recent") or []
    if recent:
        w(f"\nrecent traces ({len(recent)}):\n")
        for t in recent:
            w(f"  {t['trace_id']}  {t['root']:<30} "
              f"{t['spans']:>4} spans  {t['duration_ms']:.2f} ms\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default="http://127.0.0.1:8265",
                    help="dashboard base URL (default %(default)s)")
    ap.add_argument("--trace-id", default=None,
                    help="export only this trace (32-hex id)")
    ap.add_argument("--list", action="store_true",
                    help="print recent trace summaries instead of exporting")
    ap.add_argument("--local", action="store_true",
                    help="dump this process's recorder, no dashboard needed")
    ap.add_argument("-o", "--output", default="trace.json",
                    help="output file for the chrome-trace JSON")
    ap.add_argument("--postmortem", default=None, metavar="FILE",
                    help="render a flight-recorder postmortem JSON instead")
    args = ap.parse_args(argv)

    if args.postmortem:
        from tpu_air.observability import postmortem

        render_postmortem(postmortem.load(args.postmortem))
        return 0

    if args.local:
        from tpu_air.observability import trace_export, tracing

        if args.list:
            for t in tracing.trace_summaries():
                print(f"{t['trace_id']}  {t['root']:<30} "
                      f"{t['spans']:>4} spans  {t['duration_ms']:.2f} ms")
            return 0
        n = trace_export.export_file(args.output, trace_id=args.trace_id)
        print(f"wrote {n} spans to {args.output}")
        return 0

    base = args.url.rstrip("/")
    if args.list:
        payload = _fetch(f"{base}/api/traces")
        if not payload.get("enabled"):
            print("tracing is disabled on the target "
                  "(set TPU_AIR_TRACE=1 or call tracing.enable())",
                  file=sys.stderr)
        for t in payload.get("traces", []):
            print(f"{t['trace_id']}  {t['root']:<30} "
                  f"{t['spans']:>4} spans  {t['duration_ms']:.2f} ms"
                  + (f"  [{t['errors']} errors]" if t.get("errors") else ""))
        return 0

    url = f"{base}/api/traces/export"
    if args.trace_id:
        url += f"?trace_id={args.trace_id}"
    doc = _fetch(url)
    with open(args.output, "w") as f:
        json.dump(doc, f)
    n = doc.get("otherData", {}).get("spans", 0)
    print(f"wrote {n} spans to {args.output} "
          "(load in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
