"""Task-dispatch microbenchmark (VERDICT r4 #6; docs/NATIVE_RUNTIME.md
deviation 1).

Measures what the Python control half actually costs per task, so the
"microseconds of bookkeeping" claim is data, not argument:

* **breakdown** — sequential no-op round-trips, split by wall timestamps
  into submit->exec (schedule + pipe + deserialize), exec (user fn), and
  exec->get (seal + notify + driver fetch);
* **throughput** — pipelined no-op tasks/sec (submit N, then gather), the
  dispatch-rate ceiling of the runtime;
* **actor round-trip** — the BatchPredictor-shaped path (method call on a
  live worker process);
* **overhead share** — dispatch cost as a fraction of a W9-shaped task
  (~100 ms of real work, Overview_of_Ray.ipynb:cc-41), the workload class
  with the MOST dispatches per unit compute in the reference.

Run: ``python tools/bench_dispatch.py [--tasks 200]``.  Prints one JSON
object.  CPU-only — never touches the chip.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _noop_timed():
    t = time.time()
    # no work: exec window is just the timestamp capture
    return t, time.time()


def _sleep_100ms():
    # sleep, not spin: on a small/shared host a spinning task contends with
    # the driver for cores and the excess measures CPU starvation, not
    # dispatch.  Sleeping isolates exactly the scheduler+pipe+seal cost.
    time.sleep(0.1)
    return 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", type=int, default=200)
    args = ap.parse_args()

    import tpu_air

    tpu_air.init(num_cpus=4)
    try:
        noop = tpu_air.remote(_noop_timed)
        busy = tpu_air.remote(_sleep_100ms)

        # warm the FULL worker pool (each first task on a fresh worker pays
        # process spawn): 4 concurrent sleepers force all 4 workers up
        for r in [busy.remote() for _ in range(8)]:
            tpu_air.get(r)
        for _ in range(4):
            tpu_air.get(noop.remote())

        # -- breakdown: sequential round-trips --------------------------------
        pre_us, exec_us, post_us, total_us = [], [], [], []
        for _ in range(args.tasks):
            t_submit = time.time()
            ref = noop.remote()
            t_exec_start, t_exec_end = tpu_air.get(ref)
            t_got = time.time()
            pre_us.append((t_exec_start - t_submit) * 1e6)
            exec_us.append((t_exec_end - t_exec_start) * 1e6)
            post_us.append((t_got - t_exec_end) * 1e6)
            total_us.append((t_got - t_submit) * 1e6)

        def stats(xs):
            xs = sorted(xs)
            return {
                "p50_us": round(statistics.median(xs), 1),
                "p90_us": round(xs[int(len(xs) * 0.9)], 1),
                "mean_us": round(statistics.fmean(xs), 1),
            }

        breakdown = {
            "submit_to_exec (schedule+pipe+deserialize)": stats(pre_us),
            "exec (user fn)": stats(exec_us),
            "exec_to_get (seal+notify+fetch)": stats(post_us),
            "round_trip": stats(total_us),
        }

        # -- throughput: pipelined no-ops -------------------------------------
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(args.tasks)]
        for r in refs:
            tpu_air.get(r)
        pipelined_s = time.perf_counter() - t0
        tasks_per_sec = round(args.tasks / pipelined_s, 1)

        # -- actor method round-trip ------------------------------------------
        @tpu_air.remote
        class Echo:
            def hit(self):
                return time.time()

        a = Echo.remote()
        tpu_air.get(a.hit.remote())  # warm
        actor_us = []
        for _ in range(args.tasks):
            t_submit = time.time()
            tpu_air.get(a.hit.remote())
            actor_us.append((time.time() - t_submit) * 1e6)
        tpu_air.kill(a)

        # -- dispatch share of a W9-shaped workload ---------------------------
        # 20 tasks x 100 ms over 4 workers (Overview_of_Ray.ipynb:cc-41
        # shape). Ideal wall = 0.5 s; everything above it is scheduler +
        # pipe + seal + gather — the dispatch overhead share.
        t0 = time.perf_counter()
        refs = [busy.remote() for _ in range(20)]
        for r in refs:
            tpu_air.get(r)
        w9_wall = time.perf_counter() - t0
        w9_ideal = 20 * 0.1 / 4
        overhead_pct = round(100.0 * (w9_wall - w9_ideal) / w9_wall, 2)

        out = {
            "benchmark": "task_dispatch",
            "tasks": args.tasks,
            "breakdown": breakdown,
            "pipelined_tasks_per_sec": tasks_per_sec,
            "actor_round_trip": stats(actor_us),
            "w9_shaped": {
                "wall_s": round(w9_wall, 3),
                "ideal_s": w9_ideal,
                "dispatch_plus_skew_pct": overhead_pct,
            },
        }
        print(json.dumps(out))
    finally:
        tpu_air.shutdown()


if __name__ == "__main__":
    main()
