"""Shared host-environment insulation for the repo-root harness scripts.

This environment's sitecustomize registers a real-TPU PJRT plugin (gated on
``PALLAS_AXON_POOL_IPS``) that can wedge or fail CPU-mesh runs even under
``JAX_PLATFORMS=cpu``.  ``bench.py`` and ``__graft_entry__.py`` both need a
clean CPU subprocess environment; the recipe lives here once.
(tests/conftest.py keeps its own self-contained copy because it must run
before anything else is importable.)
"""

from __future__ import annotations

import os
import re


def cpu_env(n_devices: int | None = None) -> dict:
    """A copy of os.environ with the TPU plugin disabled and XLA:CPU forced;
    with ``n_devices`` an n-device virtual host-platform mesh is requested."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize gate for TPU plugin
    env["JAX_PLATFORMS"] = "cpu"
    xla = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", env.get("XLA_FLAGS", "")
    ).strip()
    if n_devices is not None:
        xla = f"{xla} --xla_force_host_platform_device_count={n_devices}".strip()
    env["XLA_FLAGS"] = xla
    return env
